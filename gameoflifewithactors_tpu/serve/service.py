"""SessionService: sessions × lanes × admission, behind one lock.

The serving brain. The frontend (serve/frontend.py) translates HTTP to
these methods; tests drive them directly. Responsibilities:

- **create** — admission verdict first (priced at the family's modelled
  slot bytes against the live HBM gauges), then either place into a lane
  slot, park in the backpressure queue, or refuse;
- **step** — credit a session's ``pending_steps`` debt, then **pump**:
  per lane, repeatedly dispatch ``min(positive pending)`` generations
  with the occupancy mask of the still-indebted slots. Sessions at
  different cursors ride the same dispatch — the mask freezes the ones
  that are done, so every session's trajectory is bit-identical to a
  dedicated engine of its own (the property test's claim);
- **close** — free the slot, compact the pool (ladder repack), drain
  the admission queue into the freed capacity;
- **checkpoint / resume** — one atomic ``.npz`` (packed words + a JSON
  manifest) in the utils/checkpoint.py tmp-then-``os.replace``
  discipline; resume re-places every live session and re-parks every
  queued one at its checkpointed generation;
- **lane recovery** — a lane dispatch that raises is handled in the
  supervisor's restart shape (``resilience.RestartPolicy`` backoff, a
  circuit breaker after ``max_restarts`` consecutive failures): every
  session in the lane restores from its recovery snapshot and its lost
  generations are re-credited as pending debt, so the replayed result
  is bit-identical to a never-faulted run. A lane whose circuit opens
  evicts its sessions instead of wedging the whole service.

Locking: one re-entrant service lock around anything that touches lanes
or session placement. The store and registry have their own fine-grained
locks for read paths (/healthz, /metrics) that must not wait on a pump.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import flight as obs_flight
from ..obs import spans as obs_spans
from ..obs.registry import REGISTRY, MetricsRegistry
from ..ops import bitpack
from ..resilience.supervisor import RestartPolicy
from ..memory import PoolExhausted, TilePool
from .admission import (QUEUE, REJECT, AdmissionController,
                        AdmissionRejected)
from .lanes import (LANE_LADDER, LanePool, PagedLanePool, SpecFamily,
                    paged_lane_runner, pool_capacity_for_ladder)
from .session import (CLOSED, DEAD_STATES, EVICTED, PACKED, PENDING,
                      RUNNING, Session, SessionStore)

CHECKPOINT_VERSION = 1


def encode_words(words: np.ndarray) -> str:
    """Packed (H, W/32) uint32 -> hex string (little-endian words) — the
    wire form of a grid (8x smaller than a cell-per-byte JSON array)."""
    return np.ascontiguousarray(words, dtype="<u4").tobytes().hex()


def decode_words(hexstr: str, height: int, wq: int) -> np.ndarray:
    buf = bytes.fromhex(hexstr)
    expect = height * wq * 4
    if len(buf) != expect:
        raise ValueError(
            f"grid payload is {len(buf)} bytes, expected {expect} "
            f"({height}x{wq} packed words)")
    return np.frombuffer(buf, dtype="<u4").reshape(height, wq).astype(
        np.uint32)


class SessionService:
    """The multi-tenant session manager (see module docstring)."""

    def __init__(self, *, ladder: Tuple[int, ...] = LANE_LADDER,
                 admission: Optional[AdmissionController] = None,
                 checkpoint_path: Optional[str] = None,
                 registry: MetricsRegistry = REGISTRY,
                 policy: Optional[RestartPolicy] = None,
                 warm_on_first_use: bool = True,
                 paged: bool = False,
                 paged_opts: Optional[dict] = None,
                 sleep_fn=time.sleep):
        self.ladder = tuple(sorted(set(int(c) for c in ladder)))
        self.registry = registry
        # paged serving: families whose shapes divide the slab geometry
        # pack onto a shared memory.TilePool per rule (one warm
        # executable for every geometry) instead of the capacity ladder;
        # non-dividing shapes fall back to ladder lanes unchanged. The
        # ladder arg still works — it sizes the pool via
        # pool_capacity_for_ladder when no explicit capacity is given.
        self.paged = bool(paged)
        _popts = dict(paged_opts or {})
        self._paged_tile_rows = int(_popts.pop("tile_rows", 16))
        self._paged_tile_words = int(_popts.pop("tile_words", 1))
        self._paged_capacity = _popts.pop("capacity", None)
        self._paged_chunk = _popts.pop("chunk_gens", None)
        if _popts:
            raise ValueError(f"unknown paged_opts: {sorted(_popts)}")
        self._tile_pools: Dict[tuple, TilePool] = {}
        self.admission = admission or AdmissionController(registry=registry)
        self.checkpoint_path = checkpoint_path
        self.policy = policy or RestartPolicy()
        self.warm_on_first_use = warm_on_first_use
        self._sleep = sleep_fn
        self.store = SessionStore()
        self.pools: Dict[str, LanePool] = {}
        self._lock = threading.RLock()
        # recovery snapshots: sid -> (packed words, generation) as of the
        # last checkpoint (or admission, before the first one) — what a
        # crashed lane restores from without touching disk
        self._recovery: Dict[str, Tuple[np.ndarray, int]] = {}
        self._lane_failures: Dict[str, int] = {}
        self._known_tenants: set = set()
        reg = registry
        self._m_steps = reg.counter(
            "session_steps_total", "generations stepped, per tenant")
        self._m_live = reg.gauge(
            "sessions_live", "live (packed+running) sessions, per tenant")
        self._m_lanes = reg.gauge(
            "session_lanes", "lanes allocated, per spec family")
        self._m_slots_live = reg.gauge(
            "session_lane_slots_live", "occupied lane slots, per family")
        self._m_slots_total = reg.gauge(
            "session_lane_slots_total", "allocated lane slots, per family")
        self._m_lane_bytes = reg.gauge(
            "session_lane_bytes",
            "modelled HBM bytes held by lane batches, per family")
        self._m_compactions = reg.counter(
            "session_compactions_total", "lane repacks, per family")
        self._m_recoveries = reg.counter(
            "session_lane_recoveries_total",
            "lane crash restore cycles, per family")
        self._m_evictions = reg.counter(
            "session_evictions_total",
            "sessions evicted (lane circuit open), per family")
        # per-tenant SLO surface: request-phase latencies (queue wait has
        # its own histogram in admission.py) and a recent-throughput gauge
        self._m_phase = reg.histogram(
            "session_phase_seconds",
            "request-phase latency by phase "
            "(admission / dispatch / first_step), per tenant")
        self._m_tenant_rate = reg.gauge(
            "tenant_steps_per_sec",
            "recent per-tenant step throughput on THIS process's chips "
            "(per-chip: list across procs, never sum)")
        # sid -> perf_counter at create, pending its first stepped
        # generation (the time-to-first-step phase)
        self._first_step_t0: Dict[str, float] = {}
        # tenant -> (perf_counter, cumulative steps) anchoring the rate
        self._rate_anchor: Dict[str, Tuple[float, float]] = {}
        self._tenant_steps: Dict[str, float] = {}

    # -- warm start ----------------------------------------------------------

    def warm(self, spec: dict) -> str:
        """Pre-trace a family's runner at every ladder capacity (the
        in-process half of warm start; aot/warmup.py drives this from
        the manifest's ``lanes`` entries). Returns the family key."""
        family = SpecFamily.from_spec(spec)
        with self._lock:
            pool = self._pool(family)
            pool.warm()
        return family.key

    def _pool(self, family: SpecFamily) -> LanePool:
        pool = self.pools.get(family.key)
        if pool is None:
            pool = self.pools[family.key] = self._new_pool(family)
            if self.warm_on_first_use:
                pool.warm()
        return pool

    def _new_pool(self, family: SpecFamily):
        if self.paged and self._paged_serveable(family):
            return PagedLanePool(
                family, self.ladder,
                tile_pool=self._tile_pool(family.rule),
                chunk_gens=self._paged_chunk)
        return LanePool(family, self.ladder)

    def _paged_serveable(self, family: SpecFamily) -> bool:
        return (family.backend == "packed"
                and family.height % self._paged_tile_rows == 0
                and family.wq % self._paged_tile_words == 0)

    def _tile_pool(self, rule) -> TilePool:
        """The shared per-rule tile pool: every family of this rule —
        whatever its logical geometry — pages onto the same slab and the
        same warm executable (lanes.paged_lane_runner's geometry-keyed
        cache)."""
        key = (rule.notation, self._paged_tile_rows, self._paged_tile_words)
        tp = self._tile_pools.get(key)
        if tp is None:
            capacity = self._paged_capacity or pool_capacity_for_ladder(
                self.ladder)
            tp = self._tile_pools[key] = TilePool(
                rule, int(capacity),
                tile_rows=self._paged_tile_rows,
                tile_words=self._paged_tile_words,
                name=f"serve:{rule.notation}", registry=self.registry,
                runner=paged_lane_runner(rule, self._paged_tile_rows,
                                         self._paged_tile_words))
        return tp

    # -- the session API -----------------------------------------------------

    def create(self, tenant: str, spec: dict, *,
               fill: Optional[float] = None, rng_seed: int = 0,
               cells_hex: Optional[str] = None) -> dict:
        """Admit (or queue/refuse) one session. Seeding is host-side and
        reproducible: ``fill`` draws Bernoulli cells from
        ``numpy.random.default_rng(rng_seed)`` — a client (or an oracle)
        regenerates the exact grid from the same two numbers — and
        ``cells_hex`` ships explicit packed words."""
        family = SpecFamily.from_spec(spec)
        words = self._seed_words(family, fill, rng_seed, cells_hex)
        with self._lock:
            pool = self._pool(family)
            t_adm = time.perf_counter()
            with obs_spans.span("serve.admission", tenant=tenant,
                                family=family.key):
                pressure = pool.pool_pressure(words)
                if pressure is None:
                    verdict = self.admission.decide(
                        pool.admission_cost(words), tenant=tenant)
                else:
                    needed, free = pressure
                    verdict = self.admission.decide(
                        pool.admission_cost(words), tenant=tenant,
                        pool_needed=needed, pool_free=free)
            self._m_phase.observe(time.perf_counter() - t_adm,
                                  phase="admission", tenant=tenant)
            if verdict == REJECT:
                raise AdmissionRejected(
                    f"over HBM budget and the admission queue is full "
                    f"(family {family.key})")
            sid = self.store.new_sid(tenant)
            s = Session(sid=sid, tenant=tenant, family_key=family.key,
                        spec=family.canonical_spec())
            self.store.add(s)
            self._known_tenants.add(tenant)
            self._first_step_t0[sid] = time.perf_counter()
            if verdict == QUEUE:
                s.parked = words
                self.admission.enqueue(sid, time.perf_counter())
            else:
                try:
                    self._place(pool, s, words)
                except PoolExhausted:
                    # a race (or ring tiles bound since pricing) beat the
                    # admission estimate: park rather than raise — pool
                    # OOM is a scheduling verdict, not an error
                    try:
                        s.parked = words
                        self.admission.enqueue(sid, time.perf_counter())
                    except AdmissionRejected:
                        s.parked = None
                        s.transition(CLOSED)
                        raise
            self._refresh_gauges()
            return self._info(s)

    def step(self, sid: str, n: int, *, pump: bool = True) -> dict:
        """Credit ``n`` generations of debt; by default pump immediately.
        Queued (pending) sessions accumulate debt that applies once they
        are admitted."""
        if n < 0:
            raise ValueError(f"cannot step a negative count ({n})")
        with self._lock:
            s = self.store.get(sid)
            if s.state in DEAD_STATES:
                raise ValueError(f"session {sid} is {s.state}")
            s.pending_steps += int(n)
            if pump:
                t0 = time.perf_counter()
                self.pump()
                # dispatch latency attributed to the requesting tenant:
                # how long this step call waited for its lane dispatches
                self._m_phase.observe(time.perf_counter() - t0,
                                      phase="dispatch", tenant=s.tenant)
            return self._info(s)

    def close(self, sid: str) -> dict:
        with self._lock:
            s = self.store.get(sid)
            if s.state in DEAD_STATES:
                return self._info(s)
            pool = self.pools.get(s.family_key)
            if s.placement() is not None and pool is not None:
                pool.release(s.lane_id, s.slot)
                s.lane_id = s.slot = None
                self._apply_moves(pool, pool.compact())
            s.parked = None
            s.pending_steps = 0
            s.transition(CLOSED)
            self._recovery.pop(sid, None)
            self._first_step_t0.pop(sid, None)
            self._drain_queue()
            self._refresh_gauges()
            return self._info(s)

    def info(self, sid: str) -> dict:
        with self._lock:
            return self._info(self.store.get(sid))

    def grid(self, sid: str) -> np.ndarray:
        """The session's current cells, (H, W) uint8 — host-side unpack
        of the lane slot (or the parking buffer), never a device sync."""
        with self._lock:
            s = self.store.get(sid)
            return bitpack.unpack_np(self._words_of(s))

    def grid_hex(self, sid: str) -> dict:
        with self._lock:
            s = self.store.get(sid)
            return {"sid": s.sid, "generation": s.generation,
                    "height": s.spec["height"], "width": s.spec["width"],
                    "encoding": "packed_le_u32_hex",
                    "cells_hex": encode_words(self._words_of(s))}

    # -- the pump ------------------------------------------------------------

    def pump(self) -> int:
        """Apply every session's pending debt. Returns the number of
        lane dispatches issued."""
        with self._lock:
            dispatches = 0
            for pool in list(self.pools.values()):
                for lane in list(pool.lanes.values()):
                    dispatches += self._pump_lane(pool, lane)
            self._drain_queue()
            self._refresh_gauges()
            return dispatches

    def _pump_lane(self, pool: LanePool, lane) -> int:
        dispatches = 0
        # sessions a paged dispatch could not fully provision (pool
        # pressure): their remaining debt stays booked but is ignored for
        # the rest of THIS pump — retrying would spin on the same
        # exhaustion; closes/retirement free tiles before the next pump
        stalled: set = set()
        while True:
            pend = np.zeros(lane.capacity, dtype=np.int64)
            holders: List[Optional[Session]] = [None] * lane.capacity
            for i, sid in enumerate(lane.slots):
                if sid is not None and sid not in stalled:
                    s = self.store.get(sid)
                    holders[i] = s
                    pend[i] = s.pending_steps
            if pend.max(initial=0) <= 0:
                return dispatches
            active = pend > 0
            n = int(pend[active].min())
            try:
                with obs_spans.span("lane.dispatch", lane=lane.lane_id,
                                    family=pool.family.key,
                                    generations=n,
                                    slots=int(active.sum())):
                    # ladder lanes return None (all-or-nothing); paged
                    # lanes return per-slot generations completed
                    stepped = lane.step(n, active.astype(np.uint32))
            except Exception as exc:  # noqa: BLE001 — restart is the point
                if not self._recover_lane(pool, lane, exc):
                    return dispatches  # circuit opened; lane is gone
                continue  # debts were re-credited; recompute and retry
            dispatches += 1
            now = time.perf_counter()
            self._lane_failures.pop(lane.lane_id, None)
            for i, s in enumerate(holders):
                if s is not None and active[i]:
                    done = n if stepped is None else int(stepped[i])
                    if done:
                        s.generation += done
                        s.pending_steps -= done
                        if s.state == PACKED:
                            s.transition(RUNNING)
                        self._m_steps.inc(done, tenant=s.tenant)
                        self._tenant_steps[s.tenant] = \
                            self._tenant_steps.get(s.tenant, 0.0) + done
                        t0 = self._first_step_t0.pop(s.sid, None)
                        if t0 is not None:
                            self._m_phase.observe(now - t0,
                                                  phase="first_step",
                                                  tenant=s.tenant)
                    if done < n:
                        stalled.add(s.sid)

    # -- lane recovery -------------------------------------------------------

    def _recover_lane(self, pool: LanePool, lane, exc) -> bool:
        """Restore every session in a crashed lane from its recovery
        snapshot (lost generations become re-credited debt, so the
        replay is bit-identical). Returns False when the lane's circuit
        opened — its sessions are evicted and the lane removed."""
        fam = pool.family.key
        count = self._lane_failures.get(lane.lane_id, 0) + 1
        self._lane_failures[lane.lane_id] = count
        obs_flight.note_event(
            "lane_fault", {"lane": lane.lane_id, "family": fam,
                           "attempt": count,
                           "error": f"{type(exc).__name__}: {exc}"})
        if count > self.policy.max_restarts:
            self._evict_lane(pool, lane, cause=f"circuit_open: {exc}")
            return False
        delay = self.policy.backoff(count)
        if delay > 0:
            self._sleep(delay)
        for slot, sid in enumerate(lane.slots):
            if sid is None:
                continue
            s = self.store.get(sid)
            snap = self._recovery.get(sid)
            if snap is None:  # placed this instant; its words are intact
                continue
            words, gen = snap
            lost = s.generation - gen
            lane.write(slot, words)
            s.generation = gen
            if lost > 0:
                s.pending_steps += lost
        self._m_recoveries.inc(family=fam)
        obs_flight.note_event(
            "lane_restored", {"lane": lane.lane_id, "family": fam,
                              "attempt": count})
        return True

    def _evict_lane(self, pool: LanePool, lane, *, cause: str) -> None:
        self._lane_failures.pop(lane.lane_id, None)
        for slot, sid in enumerate(lane.slots):
            if sid is None:
                continue
            s = self.store.get(sid)
            s.lane_id = s.slot = None
            s.transition(EVICTED)
            self._recovery.pop(sid, None)
            self._first_step_t0.pop(sid, None)
            self._m_evictions.inc(family=pool.family.key)
        pool.lanes.pop(lane.lane_id, None)
        obs_flight.note_event(
            "lane_evicted", {"lane": lane.lane_id,
                             "family": pool.family.key, "cause": cause})

    # -- checkpoint / resume -------------------------------------------------

    def checkpoint(self, path: Optional[str] = None) -> str:
        """One atomic .npz: a JSON manifest plus every surviving
        session's packed words. Pending step debt is persisted — a
        resumed server owes exactly what the dead one did. Also refreshes
        the in-memory recovery snapshots (lane crashes restore to the
        last checkpointed cut, same as a process crash would)."""
        path = path or self.checkpoint_path
        if not path:
            raise ValueError("no checkpoint path configured")
        with self._lock:
            manifest: dict = {"version": CHECKPOINT_VERSION,
                              "created_at": time.strftime(
                                  "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                              "sessions": []}
            arrays: Dict[str, np.ndarray] = {}
            for i, s in enumerate(self.store.all()):
                if s.state in DEAD_STATES:
                    continue
                words = self._words_of(s)
                key = f"w{i}"
                arrays[key] = words
                meta = s.to_meta()
                meta["array"] = key
                manifest["sessions"].append(meta)
                self._recovery[s.sid] = (np.array(words, copy=True),
                                         s.generation)
            arrays["manifest"] = np.array(json.dumps(manifest))
            tmp = f"{path}.tmp{os.getpid()}.npz"
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, path)
            obs_flight.note_event(
                "serve_checkpoint",
                {"path": path, "sessions": len(manifest["sessions"])})
            return path

    def resume(self, path: Optional[str] = None) -> int:
        """Reload a checkpoint into an empty service: live sessions are
        re-placed into fresh (freshly compacted) lanes at their
        checkpointed generations, queued ones re-parked. Returns the
        number of sessions restored."""
        path = path or self.checkpoint_path
        if not path:
            raise ValueError("no checkpoint path configured")
        with self._lock:
            if self.store.all():
                raise RuntimeError(
                    "resume() needs an empty service (it rebuilds "
                    "placement from scratch)")
            with np.load(path, allow_pickle=False) as data:
                manifest = json.loads(str(data["manifest"][()]))
                if manifest.get("version") != CHECKPOINT_VERSION:
                    raise ValueError(
                        f"checkpoint {path} has version "
                        f"{manifest.get('version')}, expected "
                        f"{CHECKPOINT_VERSION}")
                restored = 0
                for meta in manifest["sessions"]:
                    words = np.array(data[meta["array"]], dtype=np.uint32,
                                     copy=True)
                    family = SpecFamily.from_spec(meta["spec"])
                    pool = self._pool(family)
                    s = Session(sid=meta["sid"], tenant=meta["tenant"],
                                family_key=family.key,
                                spec=family.canonical_spec(),
                                generation=int(meta["generation"]),
                                pending_steps=int(meta["pending_steps"]))
                    self.store.add(s)
                    self._known_tenants.add(s.tenant)
                    if meta["state"] == PENDING:
                        s.parked = words
                        self.admission.enqueue(s.sid, time.perf_counter())
                    else:
                        try:
                            self._place(pool, s, words)
                        except PoolExhausted:
                            # smaller pool than at checkpoint time: park
                            # the overflow instead of failing the resume
                            s.parked = words
                            self.admission.enqueue(s.sid,
                                                   time.perf_counter())
                        else:
                            if meta["state"] == RUNNING:
                                s.transition(RUNNING)
                    restored += 1
            self._refresh_gauges()
            obs_flight.note_event("serve_resume",
                                  {"path": path, "sessions": restored})
            return restored

    # -- observability -------------------------------------------------------

    def counts(self) -> dict:
        """The /healthz body: live session/lane/queue counts, cheap."""
        with self._lock:
            lanes = sum(len(p.lanes) for p in self.pools.values())
            slots = sum(p.total_capacity() for p in self.pools.values())
            occupied = sum(p.live_count() for p in self.pools.values())
        return {"sessions": self.store.counts(),
                "lanes": lanes, "lane_slots": slots,
                "lane_slots_occupied": occupied,
                "queue_depth": self.admission.queue_depth(),
                "families": sorted(self.pools)}

    def lane_stats(self) -> List[dict]:
        with self._lock:
            out: List[dict] = []
            for pool in self.pools.values():
                out.extend(pool.stats())
            return out

    # -- internals -----------------------------------------------------------

    def _seed_words(self, family: SpecFamily, fill, rng_seed,
                    cells_hex) -> np.ndarray:
        if fill is not None and cells_hex is not None:
            raise ValueError("give either 'fill' or 'cells_hex', not both")
        if cells_hex is not None:
            return decode_words(cells_hex, family.height, family.wq)
        if fill is not None:
            rng = np.random.default_rng(int(rng_seed))
            cells = (rng.random((family.height, family.width))
                     < float(fill)).astype(np.uint8)
            return bitpack.pack_np(cells)
        return np.zeros((family.height, family.wq), dtype=np.uint32)

    def _place(self, pool: LanePool, s: Session, words: np.ndarray) -> None:
        lane_id, slot, moves = pool.place(s.sid, words)
        self._apply_moves(pool, moves)
        s.lane_id, s.slot = lane_id, slot
        if s.state == PENDING:
            s.transition(PACKED)
        self._recovery[s.sid] = (np.array(words, copy=True), s.generation)

    def _apply_moves(self, pool: LanePool, moves: dict) -> None:
        if not moves:
            return
        for sid, (lane_id, slot) in moves.items():
            moved = self.store.get(sid)
            moved.lane_id, moved.slot = lane_id, slot
        self._m_compactions.inc(family=pool.family.key)

    def _drain_queue(self) -> None:
        def cost(sid: str) -> int:
            s = self.store.maybe(sid)
            if s is None:
                return 0
            return self.pools[s.family_key].admission_cost(s.parked)

        def fits(sid: str) -> bool:
            s = self.store.maybe(sid)
            if s is None or s.state != PENDING:
                return True  # let drain pop it; the loop below skips it
            pressure = self.pools[s.family_key].pool_pressure(s.parked)
            if pressure is None:
                return True
            needed, free = pressure
            return needed <= free

        for sid in self.admission.drain(cost, time.perf_counter(),
                                        fit_fn=fits):
            s = self.store.maybe(sid)
            if s is None or s.state != PENDING:
                continue  # closed (or evicted) while parked
            pool = self.pools[s.family_key]
            words, s.parked = s.parked, None
            try:
                self._place(pool, s, words)
            except PoolExhausted:
                # the fit check raced a concurrent alloc — re-park and
                # stop draining until tiles actually free up
                s.parked = words
                self.admission.enqueue(s.sid, time.perf_counter())
                break

    def _words_of(self, s: Session) -> np.ndarray:
        if s.placement() is not None:
            return self.pools[s.family_key].lanes[s.lane_id].read(s.slot)
        if s.parked is not None:
            return np.array(s.parked, copy=True)
        raise ValueError(f"session {s.sid} is {s.state}; no grid to read")

    def _info(self, s: Session) -> dict:
        return {"sid": s.sid, "tenant": s.tenant, "state": s.state,
                "generation": s.generation,
                "pending_steps": s.pending_steps,
                "family": s.family_key, "spec": dict(s.spec),
                "lane": s.lane_id, "slot": s.slot}

    # refuse sub-window samples: back-to-back pumps would otherwise
    # publish rates computed over microsecond baselines (pure noise)
    RATE_WINDOW_SECONDS = 0.25

    def _refresh_gauges(self) -> None:
        tenants = self.store.tenants()
        for tenant in self._known_tenants:
            self._m_live.set(tenants.get(tenant, 0), tenant=tenant)
        now = time.perf_counter()
        for tenant, total in self._tenant_steps.items():
            anchor = self._rate_anchor.get(tenant)
            if anchor is None:
                self._rate_anchor[tenant] = (now, total)
                continue
            last_t, last_total = anchor
            dt = now - last_t
            if dt >= self.RATE_WINDOW_SECONDS:
                self._m_tenant_rate.set((total - last_total) / dt,
                                        tenant=tenant)
                self._rate_anchor[tenant] = (now, total)
        for key, pool in self.pools.items():
            self._m_lanes.set(len(pool.lanes), family=key)
            self._m_slots_live.set(pool.live_count(), family=key)
            self._m_slots_total.set(pool.total_capacity(), family=key)
            self._m_lane_bytes.set(pool.bytes_held(), family=key)
