// Native actor-per-cell Game of Life baseline.
//
// The reference (rikace/GameOfLifeWithActors) runs one Akka.NET actor per
// cell on the CLR thread pool — truly parallel mailbox dispatch, no GIL. The
// Python baseline in ../actor_gol.py keeps the architecture but pays the
// interpreter; this file is the same two-barrier actor protocol in C++
// with real threads, so the speedup denominator in BASELINE.md cannot be
// dismissed as "you compared against Python". Same shape as the Python
// runtime on purpose: one mailbox-serialized receive per actor (per-actor
// mutex), a shared run queue drained by a worker pool (a miniature
// dispatcher; Akka's is work-stealing, this one is a single MPMC queue —
// noted in BASELINE.md), ~13·N·M messages per generation.
//
// Protocol per generation (two barriers; see actor_gol.py's docstring for
// why one barrier races):
//   host: reset counters (quiescent) -> arm(2NM) -> broadcast TICK
//     TICK:      cell Tells alive to 8 neighbors, reports PHASE_DONE
//     NEIGHBOR:  accumulate; when all 8 in, report PHASE_DONE
//   host: wait -> arm(NM) -> broadcast COMMIT
//     COMMIT:    apply B/S rule masks, report COMMIT_DONE(new state)
//   host: wait.
//
// Exposed via a single extern "C" entry for ctypes (no pybind11 in this
// image).

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

enum Kind { TICK = 0, NEIGHBOR = 1, COMMIT = 2, PHASE_DONE = 3, COMMIT_DONE = 4, STOP = 5 };

struct Msg {
  int target;  // cell index, or -1 for the coordinator
  int kind;
  int payload;
};

struct Cell {
  uint8_t alive = 0;
  int pending = 0;       // neighbor reports still awaited this tick
  int live_reports = 0;  // live-neighbor count accumulated
  std::vector<int> neighbors;
  std::mutex mtx;        // mailbox serialization: one receive at a time
};

struct System {
  std::vector<Cell> cells;
  int birth_mask = 0, survive_mask = 0;

  // coordinator actor (reply-counting barrier)
  std::mutex coord_mtx;
  std::condition_variable coord_cv;
  int remaining = 0;
  long long population = 0;

  // dispatcher: shared run queue + worker pool
  std::deque<Msg> queue;
  std::mutex qmtx;
  std::condition_variable qcv;
  std::vector<std::thread> workers;

  void tell(int target, int kind, int payload) {
    {
      std::lock_guard<std::mutex> g(qmtx);
      queue.push_back({target, kind, payload});
    }
    qcv.notify_one();
  }

  void coordinator_receive(int /*kind*/, int payload) {
    std::lock_guard<std::mutex> g(coord_mtx);
    population += payload;
    if (--remaining == 0) coord_cv.notify_all();
  }

  void cell_receive(int id, int kind, int payload) {
    Cell& c = cells[id];
    std::lock_guard<std::mutex> g(c.mtx);
    switch (kind) {
      case TICK:
        for (int n : c.neighbors) tell(n, NEIGHBOR, c.alive);
        tell(-1, PHASE_DONE, 0);
        if (c.neighbors.empty()) tell(-1, PHASE_DONE, 0);  // isolated cell
        break;
      case NEIGHBOR:
        c.live_reports += payload;
        if (--c.pending == 0) tell(-1, PHASE_DONE, 0);
        break;
      case COMMIT: {
        const int mask = c.alive ? survive_mask : birth_mask;
        c.alive = static_cast<uint8_t>((mask >> c.live_reports) & 1);
        tell(-1, COMMIT_DONE, c.alive);
        break;
      }
      default:
        break;
    }
  }

  void work() {
    for (;;) {
      Msg m;
      {
        std::unique_lock<std::mutex> g(qmtx);
        qcv.wait(g, [&] { return !queue.empty(); });
        m = queue.front();
        queue.pop_front();
      }
      if (m.kind == STOP) return;
      if (m.target < 0)
        coordinator_receive(m.kind, m.payload);
      else
        cell_receive(m.target, m.kind, m.payload);
    }
  }

  void arm(int expected) {  // host-side, system quiescent
    std::lock_guard<std::mutex> g(coord_mtx);
    remaining = expected;
    population = 0;
  }

  void wait_phase() {
    std::unique_lock<std::mutex> g(coord_mtx);
    coord_cv.wait(g, [&] { return remaining == 0; });
  }

  void tick() {
    const int n = static_cast<int>(cells.size());
    for (auto& c : cells) {  // quiescent between barriers: no locks needed
      c.pending = static_cast<int>(c.neighbors.size());
      c.live_reports = 0;
    }
    arm(2 * n);
    for (int i = 0; i < n; ++i) tell(i, TICK, 0);
    wait_phase();
    arm(n);
    for (int i = 0; i < n; ++i) tell(i, COMMIT, 0);
    wait_phase();
  }
};

}  // namespace

extern "C" double actor_gol_run(int h, int w, const uint8_t* init, int warmup,
                                int gens, int n_workers, int torus,
                                int birth_mask, int survive_mask,
                                uint8_t* final_out, long long* final_pop) {
  System sys;
  sys.birth_mask = birth_mask;
  sys.survive_mask = survive_mask;
  sys.cells = std::vector<Cell>(static_cast<size_t>(h) * w);
  for (int r = 0; r < h; ++r)
    for (int c = 0; c < w; ++c) {
      Cell& cell = sys.cells[static_cast<size_t>(r) * w + c];
      cell.alive = init[static_cast<size_t>(r) * w + c];
      for (int dr = -1; dr <= 1; ++dr)
        for (int dc = -1; dc <= 1; ++dc) {
          if (dr == 0 && dc == 0) continue;
          int rr = r + dr, cc = c + dc;
          if (torus) {
            rr = (rr + h) % h;
            cc = (cc + w) % w;
          } else if (rr < 0 || rr >= h || cc < 0 || cc >= w) {
            continue;
          }
          cell.neighbors.push_back(rr * w + cc);
        }
    }

  for (int i = 0; i < n_workers; ++i)
    sys.workers.emplace_back([&sys] { sys.work(); });

  for (int g = 0; g < warmup; ++g) sys.tick();
  const auto t0 = std::chrono::steady_clock::now();
  for (int g = 0; g < gens; ++g) sys.tick();
  const auto t1 = std::chrono::steady_clock::now();

  for (int i = 0; i < n_workers; ++i) sys.tell(0, STOP, 0);
  for (auto& t : sys.workers) t.join();

  long long pop = 0;
  for (size_t i = 0; i < sys.cells.size(); ++i) {
    final_out[i] = sys.cells[i].alive;
    pop += sys.cells[i].alive;
  }
  if (final_pop) *final_pop = pop;
  return std::chrono::duration<double>(t1 - t0).count();
}
