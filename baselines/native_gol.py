"""ctypes wrapper + builder for the native C++ actor-per-cell baseline.

The Python actor baseline (actor_gol.py) is architecture-faithful but pays
the interpreter and the GIL; the reference's Akka.NET dispatcher is truly
parallel compiled code. This module compiles baselines/native/actor_gol.cpp
(g++, baked into the image; no pybind11, so plain ctypes) and exposes the
same ``measure()`` shape, giving BASELINE.md a defensible native
denominator for the speedup claim.

Run:  python -m baselines.native_gol [--size 64] [--gens 100] [--workers 4]
"""

from __future__ import annotations

import argparse
import ctypes
import json
import subprocess
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent / "native"
_SRC = _NATIVE_DIR / "actor_gol.cpp"
_SO = _NATIVE_DIR / "libactor_gol.so"


def build(force: bool = False) -> Path:
    """Compile the shared library if missing or stale; returns its path."""
    if not force and _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
        return _SO
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           str(_SRC), "-o", str(_SO)]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"native baseline build failed:\n{proc.stderr}")
    return _SO


_lib = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        try:
            lib = ctypes.CDLL(str(build()))
        except OSError:
            # a prebuilt .so from another toolchain (newer libstdc++,
            # different ABI) fails dlopen even though it is "fresh" by
            # mtime — rebuild from source against this machine's runtime
            lib = ctypes.CDLL(str(build(force=True)))
        lib.actor_gol_run.restype = ctypes.c_double
        lib.actor_gol_run.argtypes = [
            ctypes.c_int, ctypes.c_int,                       # h, w
            ctypes.POINTER(ctypes.c_uint8),                   # init
            ctypes.c_int, ctypes.c_int, ctypes.c_int,         # warmup, gens, workers
            ctypes.c_int, ctypes.c_int, ctypes.c_int,         # torus, birth, survive
            ctypes.POINTER(ctypes.c_uint8),                   # final_out
            ctypes.POINTER(ctypes.c_longlong),                # final_pop
        ]
        _lib = lib
    return _lib


def run(grid: np.ndarray, gens: int, *, warmup: int = 0, workers: int = 4,
        torus: bool = True, rule: str = "B3/S23") -> Tuple[np.ndarray, int, float]:
    """Run the native actor system; returns (final grid, population, seconds)."""
    from gameoflifewithactors_tpu.models.rules import parse_rule

    r = parse_rule(rule)
    grid = np.ascontiguousarray(grid, dtype=np.uint8)
    h, w = grid.shape
    out = np.zeros_like(grid)
    pop = ctypes.c_longlong(0)
    secs = _load().actor_gol_run(
        h, w,
        grid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        warmup, gens, workers, int(torus), r.birth_mask, r.survive_mask,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.byref(pop),
    )
    return out, int(pop.value), secs


def measure(size: int = 64, gens: int = 100, workers: int = 4,
            seed: str = "glider") -> dict:
    from gameoflifewithactors_tpu.models import seeds as seeds_lib

    if seed == "glider":
        grid = seeds_lib.seeded((size, size), "glider", 1, 1)
    else:
        grid = (np.random.default_rng(0).random((size, size)) < 0.5).astype(np.uint8)

    _, _, dt = run(grid, gens, warmup=3, workers=workers)
    return {
        "metric": f"native C++ actor-per-cell baseline, {size}x{size} Conway "
                  f"{seed} ({workers} workers)",
        "value": size * size * gens / dt,
        "unit": "cell-updates/sec",
        "messages_per_generation": 13 * size * size,
        "wall_seconds": dt,
        "generations": gens,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=64)
    ap.add_argument("--gens", type=int, default=100)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--seed", default="glider")
    args = ap.parse_args()
    print(json.dumps(measure(args.size, args.gens, args.workers, args.seed)))


if __name__ == "__main__":
    main()
