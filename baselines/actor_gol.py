"""Actor-per-cell Game of Life — the measured CPU baseline (config #1).

A faithful miniature of the reference's architecture (SURVEY.md §1/§4,
reconstructed from BASELINE.json's north_star: per-cell actors, neighbor
``Tell`` messages, coordinator tick barrier): every cell is an actor with a
mailbox-serialized receive; each generation the coordinator broadcasts
Tick, every cell Tells its alive/dead state to its 8 Moore neighbors, and
the coordinator barriers before the next generation. This keeps the cost
profile the reference pays — O(9·N·M) mailbox messages per generation —
which is exactly the cost the TPU stencil deletes.

Generation protocol (two barriers, so no message can cross a generation
boundary — a single barrier races: a fast neighbor's report can overtake a
slow cell's own Tick, and a cell that applied its rule early would
broadcast next-generation state):

1. host resets per-cell counters while the system is quiescent, then
   broadcasts TICK. A cell's TICK handler Tells its *current* state to all
   neighbors and reports PHASE_DONE; its NEIGHBOR handler only accumulates
   and reports PHASE_DONE when all reports are in. Coordinator barriers on
   both kinds (2·N·M).
2. host broadcasts COMMIT; each cell applies B3/S23 to its accumulated
   count and replies with its new state; coordinator barriers on N·M.

This is *deliberately* an actor runtime, not a NumPy loop: the baseline we
compare against is mailbox dispatch, and BASELINE.md requires the build to
measure it since the reference publishes no numbers. A worker pool drains a
shared run queue and executes each actor's receive under its own mailbox
lock (actor isolation: one message at a time per actor), like a miniature
Akka dispatcher.

Run:  python -m baselines.actor_gol [--size 64] [--gens 100] [--workers 4]
"""

from __future__ import annotations

import argparse
import json
import queue
import threading
import time
from typing import List, Optional

import numpy as np

TICK = "tick"
NEIGHBOR = "neighbor"
COMMIT = "commit"
PHASE_DONE = "phase_done"
COMMIT_DONE = "commit_done"
DONE_TOKEN = object()


class CellActor:
    """One grid cell: state + mailbox-serialized receive (like the
    reference's CellActor, whose mailbox serializes per-cell updates)."""

    __slots__ = ("alive", "neighbors", "pending", "live_reports", "coordinator", "lock")

    def __init__(self, alive: int):
        self.alive = alive
        self.neighbors: List["CellActor"] = []
        self.pending = 0            # neighbor reports still awaited this tick
        self.live_reports = 0       # live-neighbor count accumulated
        self.coordinator: Optional["GridCoordinatorActor"] = None
        self.lock = threading.Lock()

    def receive(self, runtime: "ActorRuntime", kind: str, payload: int) -> None:
        if kind == TICK:
            for n in self.neighbors:
                runtime.tell(n, NEIGHBOR, self.alive)
            runtime.tell(self.coordinator, PHASE_DONE, 0)
            if not self.neighbors:  # isolated cell: reports trivially complete
                runtime.tell(self.coordinator, PHASE_DONE, 0)
        elif kind == NEIGHBOR:
            self.live_reports += payload
            self.pending -= 1
            if self.pending == 0:
                runtime.tell(self.coordinator, PHASE_DONE, 0)
        elif kind == COMMIT:
            count = self.live_reports
            if self.alive:
                self.alive = 1 if count in (2, 3) else 0
            else:
                self.alive = 1 if count == 3 else 0
            runtime.tell(self.coordinator, COMMIT_DONE, self.alive)


class GridCoordinatorActor:
    """Barriers each generation phase, like the reference's reply-counting
    GridCoordinator."""

    def __init__(self, n_cells: int):
        self.n_cells = n_cells
        self.remaining = 0
        self.population = 0
        self.phase_complete = threading.Event()
        self.lock = threading.Lock()

    def receive(self, runtime: "ActorRuntime", kind: str, payload: int) -> None:
        if kind == PHASE_DONE or kind == COMMIT_DONE:
            self.population += payload
            self.remaining -= 1
            if self.remaining == 0:
                self.phase_complete.set()

    def arm(self, expected: int) -> None:
        """Called from the host between phases (system quiescent)."""
        self.remaining = expected
        self.population = 0
        self.phase_complete.clear()


class ActorRuntime:
    """Minimal dispatcher: worker threads drain a shared run queue; each
    delivery runs under the target actor's lock (mailbox serialization)."""

    def __init__(self, workers: int):
        self.queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self.threads = [
            threading.Thread(target=self._work, daemon=True) for _ in range(workers)
        ]
        for t in self.threads:
            t.start()

    def tell(self, actor, kind: str, payload: int) -> None:
        self.queue.put((actor, kind, payload))

    def _work(self) -> None:
        while True:
            item = self.queue.get()
            if item is DONE_TOKEN:
                return
            actor, kind, payload = item
            with actor.lock:
                actor.receive(self, kind, payload)

    def shutdown(self) -> None:
        for _ in self.threads:
            self.queue.put(DONE_TOKEN)
        for t in self.threads:
            t.join()


class ActorGrid:
    """Program/ActorSystem analogue: builds the grid, wires neighborhoods,
    drives ticks."""

    def __init__(self, grid: np.ndarray, workers: int = 4, torus: bool = True):
        h, w = grid.shape
        self.shape = (h, w)
        self.runtime = ActorRuntime(workers)
        self.coordinator = GridCoordinatorActor(h * w)
        self.cells = [[CellActor(int(grid[r, c])) for c in range(w)] for r in range(h)]
        for r in range(h):
            for c in range(w):
                cell = self.cells[r][c]
                cell.coordinator = self.coordinator
                for dr in (-1, 0, 1):
                    for dc in (-1, 0, 1):
                        if (dr, dc) == (0, 0):
                            continue
                        rr, cc = r + dr, c + dc
                        if torus:
                            cell.neighbors.append(self.cells[rr % h][cc % w])
                        elif 0 <= rr < h and 0 <= cc < w:
                            cell.neighbors.append(self.cells[rr][cc])
        self.generation = 0

    def tick(self) -> int:
        """One generation; returns the new population."""
        n_cells = self.shape[0] * self.shape[1]
        # phase 1: reset (quiescent — both barriers below drain the queue),
        # broadcast, accumulate
        for row in self.cells:
            for cell in row:
                cell.pending = len(cell.neighbors)
                cell.live_reports = 0
        self.coordinator.arm(2 * n_cells)
        for row in self.cells:
            for cell in row:
                self.runtime.tell(cell, TICK, 0)
        self.coordinator.phase_complete.wait()
        # phase 2: commit the rule everywhere
        self.coordinator.arm(n_cells)
        for row in self.cells:
            for cell in row:
                self.runtime.tell(cell, COMMIT, 0)
        self.coordinator.phase_complete.wait()
        self.generation += 1
        return self.coordinator.population

    def run(self, generations: int) -> int:
        pop = 0
        for _ in range(generations):
            pop = self.tick()
        return pop

    def snapshot(self) -> np.ndarray:
        h, w = self.shape
        out = np.zeros((h, w), dtype=np.uint8)
        for r in range(h):
            for c in range(w):
                out[r, c] = self.cells[r][c].alive
        return out

    def shutdown(self) -> None:
        self.runtime.shutdown()


def measure(size: int = 64, gens: int = 100, workers: int = 4, seed: str = "glider") -> dict:
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from gameoflifewithactors_tpu.models import seeds as seeds_lib

    if seed == "glider":
        grid = seeds_lib.seeded((size, size), "glider", 1, 1)
    else:
        grid = (np.random.default_rng(0).random((size, size)) < 0.5).astype(np.uint8)

    sim = ActorGrid(grid, workers=workers)
    sim.run(3)  # warmup
    t0 = time.perf_counter()
    sim.run(gens)
    dt = time.perf_counter() - t0
    sim.shutdown()
    rate = size * size * gens / dt
    return {
        "metric": f"actor-per-cell baseline, {size}x{size} Conway glider ({workers} workers)",
        "value": rate,
        "unit": "cell-updates/sec",
        "messages_per_generation": 13 * size * size,
        "wall_seconds": dt,
        "generations": gens,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=64)
    ap.add_argument("--gens", type=int, default=100)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--seed", default="glider")
    args = ap.parse_args()
    print(json.dumps(measure(args.size, args.gens, args.workers, args.seed)))


if __name__ == "__main__":
    main()
