"""Keep the tunneled-TPU PJRT plugin off the import path for CPU-only work.

When the axon tunnel is wedged (an observed recurring state of this image),
plugin *discovery* hangs ``import jax`` itself — even under
JAX_PLATFORMS=cpu — so any process that must run CPU-only has to drop
``/root/.axon_site`` from both ``sys.path`` and the PYTHONPATH it passes to
children *before* jax is first imported.

This lives at the repo root (not inside ``gameoflifewithactors_tpu``) on
purpose: importing any module of the package pulls in jax via the package
``__init__``, which is exactly what callers of this helper cannot afford yet.
"""

from __future__ import annotations

import os
import sys

_MARKER = ".axon_site"


def strip_pythonpath(environ: dict | None = None) -> str:
    """PYTHONPATH value with axon-plugin entries removed (does not mutate)."""
    env = os.environ if environ is None else environ
    return os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and _MARKER not in p)


def strip_import_path() -> None:
    """Drop axon-plugin entries from this process's sys.path and PYTHONPATH."""
    sys.path[:] = [p for p in sys.path if _MARKER not in p]
    os.environ["PYTHONPATH"] = strip_pythonpath()
