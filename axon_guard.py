"""Keep the tunneled-TPU PJRT plugin off the import path for CPU-only work.

When the axon tunnel is wedged (an observed recurring state of this image),
plugin *discovery* hangs ``import jax`` itself — even under
JAX_PLATFORMS=cpu — so any process that must run CPU-only has to drop
``/root/.axon_site`` from both ``sys.path`` and the PYTHONPATH it passes to
children *before* jax is first imported.

This lives at the repo root (not inside ``gameoflifewithactors_tpu``) on
purpose: importing any module of the package pulls in jax via the package
``__init__``, which is exactly what callers of this helper cannot afford yet.
"""

from __future__ import annotations

import os
import sys

_MARKER = ".axon_site"


def strip_pythonpath(environ: dict | None = None) -> str:
    """PYTHONPATH value with axon-plugin entries removed (does not mutate)."""
    env = os.environ if environ is None else environ
    return os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and _MARKER not in p)


def strip_import_path() -> None:
    """Drop axon-plugin entries from this process's sys.path and PYTHONPATH."""
    sys.path[:] = [p for p in sys.path if _MARKER not in p]
    os.environ["PYTHONPATH"] = strip_pythonpath()


def force_cpu(n_devices: int = 1):
    """Route this process's JAX work to an ``n_devices`` virtual-CPU platform.

    The one shared home for the image-specific staging recipe (used by
    tests/conftest.py and __graft_entry__.dryrun_multichip; bench.py builds
    the same env for a child process via :func:`strip_pythonpath`):

    1. If jax is somehow not yet imported, drop the wedge-prone plugin from
       the import path entirely. (On this image sitecustomize imports jax at
       interpreter startup, so this branch rarely fires.)
    2. Stage ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (only if
       the flag isn't already set) and ``JAX_PLATFORMS=cpu``. Both are
       consumed lazily at first *backend init*, not at jax import, so this
       works even with jax already in sys.modules — as long as no device has
       been queried yet in this process.
    3. Set the ``jax_platforms`` config too: the tunneled plugin ignores the
       env var alone, and an argument-less ``jax.devices()`` would otherwise
       initialize every registered backend, including a wedged tunnel.

    Returns the imported ``jax`` module. If backends were already
    initialized before the call, the config update is a no-op and callers
    must additionally pin work with ``jax.default_device``.
    """
    if "jax" not in sys.modules:
        strip_import_path()
    saved = {k: os.environ.get(k) for k in ("XLA_FLAGS", "JAX_PLATFORMS")}
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backends already up; caller's jax.default_device pinning applies
    # Force CPU-backend init NOW (while the staged env is visible), then
    # restore the env: both knobs are consumed at client creation, and
    # leaving them mutated would poison children this process later spawns
    # (e.g. a driver that calls dryrun then runs bench.py would silently
    # get a CPU bench — VERDICT.md round-1 Weak #2). The in-process
    # jax_platforms *config* persists, which is exactly the desired scope.
    try:
        jax.devices("cpu")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return jax
