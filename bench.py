"""Benchmark harness: north-star cell-updates/sec/chip (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no benchmark numbers (BASELINE.json "published": {});
the driver's north-star target is >=1e9 cell-updates/sec/chip on a 16384^2
grid (v5e-1), so ``vs_baseline`` reports value / 1e9 — i.e. 1.0 means the
target is exactly met. The measured Akka-style actor baseline lives in
baselines/ and BASELINE.md, not here: this file times the flagship device
path only, with the generation loop fully on-device (multi_step_packed) so
host dispatch and readback are off the measured path, matching SURVEY.md
§8's "benchmarks measure the stencil, not console I/O".

Two robustness measures for the tunneled TPU ("axon" PJRT plugin):

- ``block_until_ready`` is a **no-op** on the tunnel (it returns before the
  device work finishes), so every timed section is closed by fetching a
  scalar reduction of the result — the dependent device->host transfer
  cannot complete before the generations do.
- The tunnel is intermittently wedged (calls hang forever). The bench body
  therefore runs in a watchdog subprocess; on hang or device error it is
  re-run with JAX_PLATFORMS=cpu so one valid JSON line is always printed.
- A wedge at end-of-round must not cost the round's TPU evidence
  (VERDICT.md round-1 Weak #2), so the harness (a) preflights with
  scripts/tpu_probe.py — a <60s classification instead of a 420s watchdog
  discovery — and (b) persists every successful TPU measurement to
  results/tpu_best.json; when the tunnel is down, a persisted TPU number
  for the same requested config is preferred over a fresh CPU fallback
  (marked with "persisted": true and its recording timestamp).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

import numpy as np

NORTH_STAR_TARGET = 1e9  # cell-updates/sec/chip, 16384^2 (BASELINE.json)
WATCHDOG_S = float(os.environ.get("BENCH_WATCHDOG_S", "420"))  # per-child hang limit
# results/ is committed (artifacts/ is gitignored): a persisted TPU number
# must survive a fresh checkout, or a wedged tunnel at end-of-round silently
# costs the round's TPU evidence again (round-1 failure mode).
PERSIST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "results", "tpu_best.json")


def _provenance():
    """Load utils/provenance.py WITHOUT the package __init__ (which imports
    jax — a hang when the tunnel is wedged; this parent must stay jax-free)."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "gameoflifewithactors_tpu", "utils", "provenance.py")
    spec = importlib.util.spec_from_file_location("_bench_provenance", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _parse(argv):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--size", type=int, default=None,
                    help="grid side length (default: 16384 on TPU, 4096 on CPU)")
    ap.add_argument("--gens", type=int, default=None,
                    help="generations per timed repetition (default: autotuned)")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--backend",
                    choices=["auto", "packed", "dense", "pallas", "sparse",
                             "paged"],
                    default="auto",
                    help="auto = native pallas kernel on TPU when the shape "
                         "supports it (fastest), XLA packed otherwise")
    ap.add_argument("--rule", default="B3/S23")
    ap.add_argument("--no-probe", action="store_true",
                    help="skip the tunnel-health preflight (go straight to the watchdog)")
    ap.add_argument("--report", action="store_true",
                    help="print the provenance scoreboard of every persisted "
                         "record (bench + worklist) and exit; needs no TPU "
                         "and never imports jax")
    ap.add_argument("--telemetry-out", default=None, metavar="PATH",
                    help="write a RunReport JSON (host spans, jit compile "
                         "events, per-rep timings, stall events) for the "
                         "measured run; inspect with `python -m "
                         "gameoflifewithactors_tpu report PATH`. Written by "
                         "the measuring child, so a fresh measurement is "
                         "required (a persisted-record fallback writes none)")
    ap.add_argument("--profile-sample", type=float, default=None, metavar="S",
                    help="arm the sampling profiler in the measuring child "
                         "(one short jax.profiler window every S seconds): "
                         "op-class attribution lands in the RunReport's "
                         "profile section and a sibling .attribution.json "
                         "the persisted record points at. Off by default; "
                         "also honored via $GOLTPU_PROFILE_SAMPLE_S")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    return ap.parse_args(argv)


def _config_key(args) -> str:
    """Persistence key from the *requested* config (None size stays 'default'
    so a driver run with no args matches an earlier healthy-tunnel run)."""
    return f"{args.backend}:{args.size or 'default'}:{args.rule}"


def _attribution_path(report_path: str) -> str:
    """Sibling attribution JSON of a RunReport (the jax-free mirror of
    obs.profiler.attribution_path_for — the parent must not import the
    package)."""
    stem = (report_path[: -len(".json")]
            if report_path.endswith(".json") else report_path)
    return stem + ".attribution.json"


def _default_report_path(key: str) -> str:
    """Where a measurement's RunReport lands when the caller didn't pick:
    next to the persisted BENCH record, named by the config key — so the
    perf gate and later audits have per-measurement provenance (phase
    breakdown, compile attribution, stalls), not just the headline."""
    safe = re.sub(r"[^A-Za-z0-9._-]+", "-", key)
    return os.path.join(os.path.dirname(PERSIST_PATH),
                        f"bench_report_{safe}.json")


def _load_persisted(key: str) -> dict | None:
    try:
        with open(PERSIST_PATH) as f:
            store = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    hit = store.get(key)
    if hit is None:
        # an 'auto' request accepts a record persisted under any resolved
        # backend (auto would have picked the fastest anyway); an explicit
        # request accepts an 'auto' record ONLY if that run actually
        # resolved to the requested backend — the metric string names the
        # resolved backend, e.g. "... (pallas, 50% soup, tpu)". Serving a
        # pallas number as --backend dense evidence would be wrong by
        # orders of magnitude.
        want, rest = key.split(":", 1)
        if want == "auto":
            alts = ["pallas:" + rest, "packed:" + rest, "dense:" + rest]
            cands = [c for c in map(store.get, alts) if c is not None]
        else:
            c = store.get("auto:" + rest)
            cands = [c] if c is not None and f"({want}," in c.get("metric", "") else []
        if cands:
            hit = max(cands, key=lambda c: c["value"])
    return hit


def _persist_if_best(key: str, result: dict,
                     report_path: str | None = None) -> None:
    try:
        with open(PERSIST_PATH) as f:
            store = json.load(f)
    except (OSError, json.JSONDecodeError):
        store = {}
    prev = store.get(key)
    # fresh evidence replaces STALE evidence even when slower — a faster
    # number for a kernel that no longer exists must not block the current
    # kernel's number (VERDICT round-2 Weak #1). But only a measurement
    # with CLEANER provenance earns the unconditional replace: between two
    # records that are both uncertifiable (e.g. both dirty-tree), the
    # best-of value ratchet still decides.
    prov = _provenance()
    # embed the resolved backend's measured file set (which includes
    # bench.py itself — the timing protocol) so the record self-describes;
    # explicit_record_paths returns None for an unparseable metric, and the
    # conservative superset is then NOT embedded (locking the coarse set
    # into the record would defeat later precision improvements)
    stamp = prov.head_stamp(paths=prov.explicit_record_paths(result))
    new_uncertifiable = stamp.get("commit_dirty") or not stamp.get("commit")
    prev_stale = prev is not None and prov.staleness(prev)["stale"]
    if (prev is None or (prev_stale and not new_uncertifiable)
            or result["value"] > prev["value"]):
        # ok + commit stamp: a record must say which tree it measured so a
        # later rewrite can't hide behind it (head_stamp marks dirty-tree
        # measurements, which staleness() refuses to certify as fresh)
        store[key] = {**result, "ok": True, **stamp,
                      "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
        if report_path and os.path.exists(report_path):
            # pointer to the measurement's RunReport (repo-relative so a
            # fresh checkout resolves it)
            repo_root = os.path.dirname(os.path.dirname(PERSIST_PATH))
            store[key]["telemetry_report"] = os.path.relpath(
                report_path, repo_root)
            apath = _attribution_path(report_path)
            if os.path.exists(apath):
                # profiler-armed measurement: the op-class attribution
                # summary rides next to the report (ISSUE 18)
                store[key]["profile_attribution"] = os.path.relpath(
                    apath, repo_root)
        os.makedirs(os.path.dirname(PERSIST_PATH), exist_ok=True)
        tmp = PERSIST_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(store, f, indent=1)
            f.write("\n")
        os.replace(tmp, PERSIST_PATH)


def report() -> None:
    """Provenance scoreboard: every persisted record in results/ with its
    commit stamp and current staleness — one glance answers "which numbers
    describe the code at HEAD and which describe a predecessor". Stdlib
    only (safe while the tunnel is wedged)."""
    prov = _provenance()
    repo = os.path.dirname(os.path.abspath(__file__))
    rows = []
    for path, label in ((PERSIST_PATH, "bench"),
                        (os.path.join(repo, "results", "tpu_worklist.json"),
                         "worklist")):
        try:
            with open(path) as f:
                store = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        for key, rec in sorted(store.items()):
            if not isinstance(rec, dict):
                continue
            # worklist store keys ARE item names: select the per-item
            # measured path set for records that predate measured_paths
            st = prov.staleness(rec, item=key if label == "worklist" else None)
            rows.append({
                "source": label, "key": key,
                "ok": rec.get("ok"),
                "value": rec.get("value"),
                "unit": rec.get("unit"),
                "commit": rec.get("commit"),
                "recorded_at": rec.get("recorded_at"),
                "stale": st["stale"],
                "reason": st["reason"],
            })
    # standalone single-measurement artifacts (config5 captures, ad-hoc
    # runs): anything in results/ with a metric field that isn't one of
    # the two stores above. Weak-scaling JSONL series are skipped — they
    # are many records per file with no single headline value to flag.
    seen = {PERSIST_PATH, os.path.join(repo, "results", "tpu_worklist.json")}
    res_dir = os.path.join(repo, "results")
    for name in sorted(os.listdir(res_dir) if os.path.isdir(res_dir) else []):
        path = os.path.join(res_dir, name)
        if path in seen or not name.endswith(".json"):
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue  # JSONL series or non-record files
        if not isinstance(rec, dict) or "metric" not in rec:
            continue
        st = prov.staleness(rec)
        rows.append({
            "source": "artifact", "key": name[:-5],
            "ok": rec.get("ok", True),  # standalone artifacts predate ok
            "value": rec.get("value"),
            "unit": rec.get("unit"),
            "commit": rec.get("commit"),
            "recorded_at": rec.get("recorded_at"),
            "stale": st["stale"],
            "reason": st["reason"],
        })
    head = prov.git_head()
    fresh = sum(1 for r in rows if r["ok"] and not r["stale"])
    for r in rows:
        flag = ("FRESH" if r["ok"] and not r["stale"]
                else "stale" if r["ok"] else "FAILED")
        val = (f"{r['value']:.3g} {r['unit'] or ''}".strip()
               if isinstance(r["value"], (int, float)) else "-")
        why = f"  [{r['reason']}]" if r["stale"] else ""
        print(f"{flag:6} {r['source']:8} {r['key']:28} {val:26} "
              f"@{r['commit'] or '?'} {r['recorded_at'] or '?'}{why}")
    print(json.dumps({"report": True, "head": head, "records": len(rows),
                      "fresh_ok": fresh}))


def run_bench(args) -> None:
    import jax

    from gameoflifewithactors_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    # warm start (aot/cache.py): the autotune probe + repetitions reuse
    # persisted executables, so a re-measurement of an unchanged kernel
    # pays ~zero compile — and the warmup/compile split in the telemetry
    # report attributes what was served from disk (cache_hit events)
    from gameoflifewithactors_tpu.aot import cache as aot_cache

    aot_cache.ensure_persistent_cache()
    import jax.numpy as jnp

    from gameoflifewithactors_tpu.models.generations import GenRule, parse_any
    from gameoflifewithactors_tpu.models.ltl import LtLRule
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.ops.packed import multi_step_packed
    from gameoflifewithactors_tpu.ops.pallas_stencil import (
        default_interpret,
        multi_step_pallas,
    )
    from gameoflifewithactors_tpu.ops.stencil import Topology, multi_step

    import contextlib

    telem = None
    if args.telemetry_out:
        from gameoflifewithactors_tpu.obs import begin_run_telemetry

        # an in-process stall event (naming the last-completed span)
        # escapes on stderr BEFORE the parent's subprocess watchdog kills
        # a wedged child — the diagnostics the wedged-probe runs never had
        profile_sample = args.profile_sample
        if profile_sample is None and os.environ.get("GOLTPU_PROFILE_SAMPLE_S"):
            profile_sample = float(os.environ["GOLTPU_PROFILE_SAMPLE_S"])
        telem = begin_run_telemetry(stall_deadline=float(
            os.environ.get("BENCH_STALL_DEADLINE_S", "60")),
            profile_sample=profile_sample)

    def _span(name, **attrs):
        if telem is None:
            return contextlib.nullcontext()
        from gameoflifewithactors_tpu.obs import span

        return span(name, **attrs)

    def _watched(label):
        if telem is None or telem.watchdog is None:
            return contextlib.nullcontext()
        return telem.watchdog.watch(label)

    platform = jax.devices()[0].platform
    side = args.size or (16384 if platform != "cpu" else 4096)
    rule = parse_any(args.rule)
    from gameoflifewithactors_tpu.models.elementary import ElementaryRule

    if isinstance(rule, ElementaryRule):
        raise SystemExit(
            f"{rule.notation} is a 1D (elementary) rule; this bench times 2D "
            "grids. Drive ops.elementary directly (see examples/wolfram.py)")
    explicitly_packed = args.backend == "packed"
    explicitly_pallas = args.backend == "pallas"
    if args.backend == "auto":
        # pallas (temporal-blocked Mosaic kernel, ~2.8x the XLA SWAR rate on
        # chip) when native and the shape qualifies; XLA packed elsewhere
        from gameoflifewithactors_tpu.ops.pallas_stencil import supported

        native = platform == "tpu"
        args.backend = (
            "pallas" if native and supported((side, side // 32), on_tpu=True)
            else "packed")
        sys.stderr.write(f"auto backend -> {args.backend}\n")
    def _route_rule(want_packed: bool, packed_label: str) -> None:
        target = "packed" if want_packed and side % 32 == 0 else "dense"
        if args.backend != target:
            sys.stderr.write(
                f"note: rule {rule.notation} runs on the "
                f"{packed_label if target == 'packed' else 'dense'} "
                f"path; --backend {args.backend} -> {target}\n")
        args.backend = target

    if isinstance(rule, GenRule) and args.backend == "pallas":
        # the Generations bit-plane kernel is honored only on EXPLICIT
        # request at shapes it supports (auto stays on the measured packed
        # path until the pallas_generations worklist item proves otherwise);
        # the supported() gate budgets VMEM for all b planes, like engine.py
        from gameoflifewithactors_tpu.ops.packed_generations import n_planes
        from gameoflifewithactors_tpu.ops.pallas_stencil import supported

        ok = (explicitly_pallas and side % 32 == 0
              and supported((side, side // 32), on_tpu=platform == "tpu",
                            planes=n_planes(rule.states)))
        if not ok:
            _route_rule(True, "bit-plane packed")
    elif isinstance(rule, GenRule) and args.backend not in ("dense", "paged"):
        # multi-state rules have a bit-plane packed path (~4x the dense
        # rate on CPU) when the width packs (32 cells/word)
        _route_rule(True, "bit-plane packed")
    elif isinstance(rule, LtLRule) and args.backend == "pallas":
        # the radius-r temporal-blocked kernel is honored on EXPLICIT
        # request at shapes its gate accepts (auto stays on the measured
        # packed path until the ltl_pallas worklist item proves otherwise)
        from gameoflifewithactors_tpu.ops.pallas_stencil import ltl_supported

        ok = (explicitly_pallas and side % 32 == 0
              and ltl_supported((side, side // 32), rule,
                                on_tpu=platform == "tpu"))
        if not ok:
            _route_rule(platform == "tpu" and rule.states == 2,
                        "bit-sliced packed")
    elif isinstance(rule, LtLRule) and args.backend not in ("dense", "sparse",
                                                            "paged"):
        # LtL: bit-sliced packed path (binary) / bit-plane stack (C >= 3
        # decay) on explicit request; on TPU auto, binary rides packed
        # (measured) while C >= 3 stays on the byte path until the plane
        # path has an on-chip number (engine routing). An explicit sparse
        # request passes through to the activity-tiled engine.
        _route_rule(explicitly_packed
                    or (platform == "tpu" and rule.states == 2),
                    "bit-sliced packed" if rule.states == 2
                    else "bit-plane packed")

    def sync(x) -> int:
        """Force completion: block (a no-op on the tunnel), then fetch a
        scalar that depends on every word of the result."""
        x.block_until_ready()
        return int(jnp.sum(x.astype(jnp.uint32))) & 0xFFFF

    rng = np.random.default_rng(0)
    if args.backend in ("sparse", "paged"):
        # config #5's shape: a Gosper gun in a huge empty field (a random
        # soup would always take the dense fallback, and a paged pool
        # would degenerate to fully dense)
        from gameoflifewithactors_tpu.models import seeds as seeds_lib

        grid = seeds_lib.seeded((side, side), "gosper_gun", side // 2, side // 2)
    elif getattr(rule, "states", 2) > 2:
        # uniform 0..C-1 state soup for multi-state rules (Generations
        # and C >= 3 LtL), every layout — keeps dense-vs-packed
        # comparisons apples-to-apples
        grid = rng.integers(0, rule.states, size=(side, side), dtype=np.uint8)
    else:
        grid = rng.integers(0, 2, size=(side, side), dtype=np.uint8)
    if isinstance(rule, GenRule) and args.backend == "pallas":
        from gameoflifewithactors_tpu.ops.packed_generations import (
            pack_generations_for,
        )
        from gameoflifewithactors_tpu.ops.pallas_stencil import (
            multi_step_pallas_generations,
        )

        state = pack_generations_for(jnp.asarray(grid), rule)
        interpret = default_interpret()
        run = lambda s, n: multi_step_pallas_generations(
            s, int(n), rule=rule, topology=Topology.TORUS,
            interpret=interpret, donate=True)
    elif isinstance(rule, GenRule) and args.backend == "packed":
        from gameoflifewithactors_tpu.ops.packed_generations import (
            multi_step_packed_generations,
            pack_generations_for,
        )

        state = pack_generations_for(jnp.asarray(grid), rule)
        run = lambda s, n: multi_step_packed_generations(
            s, n, rule=rule, topology=Topology.TORUS, donate=True)
    elif isinstance(rule, LtLRule) and args.backend == "pallas":
        from gameoflifewithactors_tpu.ops.pallas_stencil import (
            multi_step_ltl_pallas,
        )

        state = jnp.asarray(bitpack.pack_np(np.asarray(grid)))
        interpret = default_interpret()
        run = lambda s, n: multi_step_ltl_pallas(
            s, int(n), rule=rule, topology=Topology.TORUS,
            interpret=interpret, donate=True)
    elif isinstance(rule, LtLRule) and args.backend == "packed":
        if rule.states >= 3:
            # multi-state decay: the bit-plane stack driven by radius-r
            # interval counts (ops/packed_ltl.step_ltl_planes)
            from gameoflifewithactors_tpu.ops.packed_generations import (
                pack_generations_for,
            )
            from gameoflifewithactors_tpu.ops.packed_ltl import (
                multi_step_ltl_planes,
            )

            state = pack_generations_for(jnp.asarray(grid), rule)
            run = lambda s, n: multi_step_ltl_planes(
                s, n, rule=rule, topology=Topology.TORUS, donate=True)
        else:
            from gameoflifewithactors_tpu.ops.packed_ltl import (
                multi_step_ltl_packed,
            )

            state = jnp.asarray(bitpack.pack_np(np.asarray(grid)))
            run = lambda s, n: multi_step_ltl_packed(
                s, n, rule=rule, topology=Topology.TORUS, donate=True)
    elif args.backend == "packed":
        state = jnp.asarray(bitpack.pack_np(np.asarray(grid)))
        run = lambda s, n: multi_step_packed(s, n, rule=rule, topology=Topology.TORUS,
                                             donate=True)
    elif args.backend == "pallas":
        state = jnp.asarray(bitpack.pack_np(np.asarray(grid)))
        interpret = default_interpret()
        run = lambda s, n: multi_step_pallas(
            s, int(n), rule=rule, topology=Topology.TORUS, interpret=interpret,
            donate=True)
    elif args.backend == "sparse":
        from gameoflifewithactors_tpu.ops.sparse import SparseEngineState

        sparse_state = SparseEngineState(
            jnp.asarray(bitpack.pack_np(np.asarray(grid))), rule,
            topology=Topology.TORUS)  # same boundary as the other backends

        def run(s, n):
            sparse_state.step(int(n))
            return sparse_state.packed

        state = sparse_state.packed
    elif args.backend == "paged":
        # page-table grids over the tile pool (memory/): footprint and
        # compute scale with the gun's live region, measured on the same
        # seed as sparse so the two activity-scaling backends compare
        from gameoflifewithactors_tpu.memory import PagedEngineState

        paged_state = PagedEngineState(
            jnp.asarray(bitpack.pack_np(np.asarray(grid))), rule,
            topology=Topology.TORUS)
        paged_state.pool.warm()

        def run(s, n):
            paged_state.step(int(n))
            return paged_state.packed

        state = paged_state.packed
    elif isinstance(rule, GenRule):
        from gameoflifewithactors_tpu.ops.generations import multi_step_generations

        state = jnp.asarray(grid)
        run = lambda s, n: multi_step_generations(s, n, rule=rule, topology=Topology.TORUS,
                                                  donate=True)
    elif isinstance(rule, LtLRule):
        from gameoflifewithactors_tpu.ops.ltl import multi_step_ltl

        state = jnp.asarray(grid)
        run = lambda s, n: multi_step_ltl(s, n, rule=rule, topology=Topology.TORUS,
                                          donate=True)
    else:
        state = jnp.asarray(grid)
        run = lambda s, n: multi_step(s, n, rule=rule, topology=Topology.TORUS,
                                      donate=True)

    # warmup: compile + a few generations (>= the pallas temporal depth, so
    # the kernel itself compiles here, not inside the autotune timing)
    with _span("bench.warmup", backend=args.backend), _watched("bench.warmup"):
        state = run(state, 10)
        sync(state)

    gens = args.gens
    if gens is None:
        # autotune: aim for ~4s per repetition. The probe must be long
        # enough that the tunnel's ~65 ms/dispatch latency doesn't swamp
        # per-gen time (at the pallas path's measured 1.8e12 updates/s a
        # 10-gen probe was >95% latency and the sized repetitions then ran
        # ~7x under the chip's sustained rate), hence 64 gens and a 16384
        # cap rather than the earlier 10 and 2000.
        t0 = time.perf_counter()
        with _span("bench.autotune"), _watched("bench.autotune"):
            state = run(state, 64)
            sync(state)
        per_gen = (time.perf_counter() - t0) / 64
        gens = max(10, min(16384, int(4.0 / max(per_gen, 1e-7))))

    cells = side * side
    best = 0.0
    for rep in range(args.repeats):
        t0 = time.perf_counter()
        with _span("bench.rep", rep=rep, gens=gens), _watched(f"bench.rep{rep}"):
            state = run(state, gens)
            sync(state)
        dt = time.perf_counter() - t0
        best = max(best, cells * gens / dt)
        if rep == 0 and args.gens is None and dt < 2.0:
            # the 64-gen probe over-estimates per-gen time by the tunnel's
            # ~65 ms dispatch latency, sizing repetitions too short for the
            # fastest backends; the first full repetition measures per-gen
            # time to ~2% — re-size the remaining repetitions from it
            gens = max(10, min(16384, int(4.0 * gens / dt)))

    seed_note = ("gosper-gun" if args.backend in ("sparse", "paged")
                 else "uniform state soup" if getattr(rule, "states", 2) > 2
                 else "50% soup")
    print(json.dumps({
        "metric": f"cell-updates/sec/chip, {side}x{side} {rule.notation} ({args.backend}, {seed_note}, {platform})",
        "value": best,
        "unit": "cell-updates/sec",
        "vs_baseline": best / NORTH_STAR_TARGET,
    }))
    if telem is not None:
        run_report = telem.finish(
            config={"bench": True, "side": side, "rule": rule.notation,
                    "backend": args.backend, "platform": platform,
                    "gens_per_rep": gens, "repeats": args.repeats,
                    "best_cell_updates_per_sec": best},
            halo_bytes={"model_per_gen": 0, "measured_per_gen": None})
        run_report.save(args.telemetry_out)
        sys.stderr.write(
            f"telemetry report written: {args.telemetry_out}\n")
        if run_report.profile is not None:
            apath = _attribution_path(args.telemetry_out)
            with open(apath, "w") as f:
                json.dump(run_report.profile, f, indent=1)
                f.write("\n")
            sys.stderr.write(f"profile attribution written: {apath}\n")


def main() -> None:
    args = _parse(sys.argv[1:])
    if args.report:
        report()
        return
    if args.child:
        run_bench(args)
        return

    def _partial(stream) -> str:
        if stream is None:
            return ""
        return stream.decode(errors="replace") if isinstance(stream, bytes) else stream

    repo = os.path.dirname(os.path.abspath(__file__))
    key = _config_key(args)
    child_argv = [a for a in sys.argv[1:] if a != "--no-probe"]
    # every measuring child writes a RunReport next to the BENCH record
    # it may persist (per-measurement provenance for the perf gate); an
    # explicit --telemetry-out still wins
    report_defaulted = args.telemetry_out is None
    report_path = args.telemetry_out
    if report_defaulted:
        report_path = _default_report_path(key)
        child_argv += ["--telemetry-out", report_path]
    cmd = [sys.executable, os.path.abspath(__file__), "--child", *child_argv]

    def _quarantine_cpu_report() -> None:
        # a CPU-platform measurement must not overwrite the TPU report
        # the persisted record's telemetry_report pointer names — park it
        # under a .cpu suffix instead (only for the defaulted path; an
        # explicit --telemetry-out is the caller's own business)
        if report_defaulted and os.path.exists(report_path):
            os.replace(report_path, report_path[:-5] + ".cpu.json")
        # the attribution summary follows its report into quarantine —
        # CPU host-track attribution must not pose as the TPU record's
        apath = _attribution_path(report_path)
        if report_defaulted and os.path.exists(apath):
            os.replace(apath, apath[:-5] + ".cpu.json")

    tpu_ok = True
    if not args.no_probe:
        sys.path.insert(0, os.path.join(repo, "scripts"))
        from tpu_probe import probe

        health = probe(timeout=float(os.environ.get("TPU_PROBE_TIMEOUT_S", "60")))
        sys.stderr.write(f"tpu_probe: {health['status']} ({health['detail']})\n")
        tpu_ok = health["status"] == "healthy"

    if tpu_ok:
        try:
            r = subprocess.run(cmd, capture_output=True, text=True, timeout=WATCHDOG_S)
            if r.returncode == 0 and r.stdout.strip():
                sys.stderr.write(r.stderr)
                # last stdout line that parses as a measurement (runtime
                # libraries may append noise after run_bench's print)
                result = line = None
                for cand in reversed(r.stdout.strip().splitlines()):
                    try:
                        parsed = json.loads(cand)
                        if isinstance(parsed, dict) and "metric" in parsed:
                            result, line = parsed, cand
                            break
                    except json.JSONDecodeError:
                        continue
                if result is not None:
                    if "cpu" not in result["metric"]:
                        _persist_if_best(key, result, report_path)
                    else:
                        _quarantine_cpu_report()
                    print(line)
                    return
                sys.stderr.write("\nbench child printed no JSON measurement; falling back\n")
            else:
                sys.stderr.write(r.stderr)
                sys.stderr.write(f"\nbench child failed (rc={r.returncode}); falling back\n")
        except subprocess.TimeoutExpired as e:
            sys.stderr.write(_partial(e.stdout))
            sys.stderr.write(_partial(e.stderr))
            sys.stderr.write(f"\nbench child hung >{WATCHDOG_S}s (TPU tunnel wedged?); falling back\n")
    else:
        sys.stderr.write("TPU tunnel not healthy; skipping the TPU attempt\n")

    # a persisted TPU measurement from earlier in the round beats a fresh
    # CPU-fallback number: the metric is defined for TPU hardware
    persisted = _load_persisted(key)
    if persisted is not None:
        prov = _provenance().staleness(persisted)
        # the staleness verdict prints ONCE (the WARNING line below) and
        # lands in the served JSON once (stale_reason) — not echoed again
        # on the "using persisted" line or the NEEDS RECAPTURE tail
        # (BENCH_r05's tail carried the measured-paths diff twice)
        note = "" if prov["stale"] else f" ({prov['reason']})"
        sys.stderr.write(
            f"using persisted TPU measurement recorded at "
            f"{persisted.get('recorded_at')}{note}\n")
        # re-derive staleness from HEAD on every serve: flags a previous
        # serve baked into the persisted file must not leak through
        out = {k: v for k, v in persisted.items()
               if k not in ("stale", "stale_reason", "needs_recapture")}
        out["persisted"] = True
        if prov["stale"]:
            # the measured code path changed since this record's commit:
            # the number describes a PREDECESSOR of HEAD's kernel. Serve it
            # (a stale TPU number still beats a fresh CPU number for a
            # TPU-defined metric) but never silently — and never
            # mistakably: a distinct machine-readable flag plus a tail
            # line AFTER the JSON, so a driver that only keeps the last
            # lines of output still can't read a stale 2200x as fresh
            # (BENCH_r05 failure mode).
            sys.stderr.write(f"WARNING: persisted record is STALE — {prov['reason']}\n")
            out["stale"] = True
            out["stale_reason"] = prov["reason"]
            out["needs_recapture"] = True
        print(json.dumps(out))
        if prov["stale"]:
            sys.stderr.write(
                f"NEEDS RECAPTURE: vs_baseline={out.get('vs_baseline', 0):.3g} "
                f"above is a STALE persisted TPU record "
                f"(@{out.get('commit', '?')}, {out.get('recorded_at', '?')}; "
                "stale_reason in the JSON above). Re-run bench.py in a "
                "healthy tunnel window before citing it.\n")
        return

    # when the tunnel is wedged the axon PJRT plugin hangs `import jax`
    # itself, so the CPU fallback must also drop it from PYTHONPATH
    import axon_guard

    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": axon_guard.strip_pythonpath()}
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=WATCHDOG_S, env=env)
    except subprocess.TimeoutExpired as e:
        sys.stderr.write(_partial(e.stdout))
        sys.stderr.write(_partial(e.stderr))
        sys.stderr.write(f"\nCPU fallback also exceeded {WATCHDOG_S}s; no measurement\n")
        raise SystemExit(1)
    sys.stdout.write(r.stdout)
    sys.stderr.write(r.stderr)
    _quarantine_cpu_report()  # the fallback child is CPU by construction
    if r.returncode != 0:
        raise SystemExit(r.returncode)


if __name__ == "__main__":
    main()
