"""A glider flying forever across an unbounded paged universe.

No grid was ever allocated for this universe: the paged subsystem
(gameoflifewithactors_tpu/memory/) binds physical tiles from a fixed
pool only where live structure is, allocates new pages at the glider's
advancing wake front, and retires the dead pages behind it — so the
glider's footprint stays a constant handful of tiles however far it
flies. Run it for a million generations and the pool gauges read the
same as at generation 100.

Mid-flight the universe checkpoints (utils/checkpoint.save_paged — the
sparse page list, never a dense rectangle), restores into a fresh pool,
and the copy must be bit-identical to the original for the rest of the
run: that equivalence is asserted, so this example doubles as the CI
paged-smoke gate (run under GOLTPU_SANITIZE=1 the whole flight holds
retrace_budget(0) after warm).

    python examples/unbounded_glider.py --gens 1024
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

import numpy as np

GLIDER = ((0, 1), (1, 2), (2, 0), (2, 1), (2, 2))  # flies down-right


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--gens", type=int, default=1024,
                    help="generations to fly (glider advances 1 cell "
                         "diagonally per 4)")
    ap.add_argument("--tile-rows", type=int, default=16)
    ap.add_argument("--tile-words", type=int, default=1)
    ap.add_argument("--capacity", type=int, default=64,
                    help="pool tiles — constant however far the glider "
                         "flies")
    ap.add_argument("--report-every", type=int, default=256)
    ap.add_argument("--checkpoint", default=None, metavar="PATH",
                    help="where the mid-flight checkpoint lands "
                         "(default: a temp file, removed after)")
    args = ap.parse_args(argv)

    from gameoflifewithactors_tpu.analysis import sanitizers
    from gameoflifewithactors_tpu.memory import PagedUniverse, TilePool
    from gameoflifewithactors_tpu.models.generations import parse_any
    from gameoflifewithactors_tpu.serve.lanes import paged_lane_runner
    from gameoflifewithactors_tpu.utils import checkpoint as ckpt

    # the process-wide shared runner cache: every pool of this (rule,
    # slab geometry) — including the restored twin's fresh pool below —
    # reuses ONE warm executable, so a restore costs zero compiles
    rule = parse_any("B3/S23")
    runner = paged_lane_runner(rule, args.tile_rows, args.tile_words)

    def make_pool(name: str) -> TilePool:
        return TilePool(rule, args.capacity, tile_rows=args.tile_rows,
                        tile_words=args.tile_words, name=name,
                        runner=runner)

    cells = np.zeros((8, 8), np.uint8)
    for y, x in GLIDER:
        cells[y, x] = 1
    u = PagedUniverse(rule, pool=make_pool("glider"))
    u.seed_cells(cells, origin=(1, 1))
    seed_bbox = u.live_bbox_cells()
    u.pool.warm()

    # after warm, the whole flight — page allocation at the front, page
    # retirement behind, every step chunk — must reuse the warm
    # executables; a single compile here is a bug
    budget = sanitizers.retrace_budget(0)
    budget.__enter__()

    t0 = time.perf_counter()
    done = 0
    mid = args.gens // 2
    ckpt_path = args.checkpoint
    tmp_dir = None
    if ckpt_path is None:
        tmp_dir = tempfile.mkdtemp(prefix="goltpu_glider_")
        ckpt_path = os.path.join(tmp_dir, "glider.npz")
    twin = None
    try:
        while done < args.gens:
            n = min(args.report_every, args.gens - done)
            if twin is None and done + n >= mid:
                n = mid - done or n
            u.step(n)
            if twin is not None:
                twin.step(n)
            done += n
            stats = u.pool.stats()
            print(f"gen {u.generation:7d}  pop {u.population():3d}  "
                  f"pool {stats['in_use']}/{stats['capacity']} tiles  "
                  f"({done / (time.perf_counter() - t0):8.1f} gens/s)")
            if twin is None and done >= mid:
                # mid-flight: checkpoint, restore into a FRESH pool, and
                # fly both for the rest of the run
                ckpt.save_paged(u, ckpt_path)
                grid2, _meta = ckpt.load_paged(
                    ckpt_path, pool=make_pool("glider-restore"))
                grid2.pool.warm()
                twin = PagedUniverse(rule, pool=grid2.pool)
                twin.grid = grid2
                print(f"checkpointed at gen {u.generation} -> {ckpt_path} "
                      f"({os.path.getsize(ckpt_path)} bytes, "
                      f"{len(grid2.pages)} pages)")
    finally:
        budget.__exit__(None, None, None)
        if tmp_dir is not None:
            import shutil

            shutil.rmtree(tmp_dir, ignore_errors=True)

    origin, snap = u.snapshot_cells()
    t_origin, t_snap = twin.snapshot_cells()
    if origin != t_origin or not np.array_equal(snap, t_snap):
        print("FAIL: restored universe diverged from the original",
              file=sys.stderr)
        return 1
    if u.population() != 5:
        print(f"FAIL: glider lost cells (pop {u.population()})",
              file=sys.stderr)
        return 1
    bbox = u.live_bbox_cells()
    flown = bbox[0] - seed_bbox[0]
    print(f"glider flew {flown} cells diagonally over {u.generation} gens "
          f"(bbox {seed_bbox} -> {bbox}); restored twin bit-identical; "
          f"pool constant at {u.pool.stats()['in_use']} tiles")
    if flown < args.gens // 4 - 2:
        print("FAIL: glider did not advance (expected ~gens/4 cells)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
