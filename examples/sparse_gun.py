"""A Gosper gun firing into a huge, mostly-empty universe (config #5 shape).

The sparse backend's activity tiling makes compute scale with the CHANGED
area, not the grid area — a 65536² universe with one gun costs ~6 active
tiles per generation (results/config5_sparse_65536_tpu.json). This example
runs a scaled-down version and prints the live-cell count every few hundred
generations (the gun emits a glider every 30).

    python examples/sparse_gun.py --side 4096 --gens 900
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--side", type=int, default=4096)
    ap.add_argument("--gens", type=int, default=900)
    ap.add_argument("--report-every", type=int, default=300)
    args = ap.parse_args(argv)

    from gameoflifewithactors_tpu import Engine
    from gameoflifewithactors_tpu.models import seeds
    from gameoflifewithactors_tpu.ops.stencil import Topology

    grid = np.asarray(seeds.seeded((args.side, args.side), "gosper_gun",
                                   args.side // 2, args.side // 2))
    # DEAD boundary: escaped gliders die at the edge instead of wrapping
    # around to destroy the gun
    eng = Engine(grid, "B3/S23", topology=Topology.DEAD, backend="sparse")
    t0 = time.perf_counter()
    done = 0
    while done < args.gens:
        n = min(args.report_every, args.gens - done)
        eng.step(n)
        done += n
        print(f"gen {done:6d}  pop {eng.population():6d}  "
              f"({done / (time.perf_counter() - t0):8.1f} gens/s)")


if __name__ == "__main__":
    sys.exit(main())
