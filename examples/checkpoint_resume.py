"""Checkpoint/resume round-trip: run, save, crash, resume, verify.

The reference has no persistence (SURVEY.md §6); here a universe is one
array and resume is bit-exact. This example drives the Engine + checkpoint
API the way a long-running experiment would: advance, snapshot to disk,
"crash", reload into a FRESH engine, advance both, and prove the resumed
trajectory identical to the uninterrupted one.

    python examples/checkpoint_resume.py --side 512 --gens 300
"""

from __future__ import annotations

import argparse
import sys
import tempfile

import numpy as np


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--side", type=int, default=512)
    ap.add_argument("--gens", type=int, default=300)
    ap.add_argument("--rule", default="B3/S23")
    args = ap.parse_args(argv)

    from gameoflifewithactors_tpu import Engine
    from gameoflifewithactors_tpu.models import seeds
    from gameoflifewithactors_tpu.utils import checkpoint

    grid = np.asarray(seeds.seeded((args.side, args.side), "gosper_gun", 8, 8))
    half = args.gens // 2

    # the uninterrupted run
    ref = Engine(grid, args.rule)
    ref.step(args.gens)

    # the interrupted one: save at the halfway point...
    eng = Engine(grid, args.rule)
    eng.step(half)
    with tempfile.TemporaryDirectory() as tmpdir:
        path = f"{tmpdir}/halfway.npz"
        checkpoint.save(eng, path)
        print(f"checkpointed at gen {eng.generation} -> {path}")
        del eng  # ...crash...

        # ...and resume into a fresh engine
        eng2 = checkpoint.load_engine(path)
    print(f"resumed at gen {eng2.generation}")
    eng2.step(args.gens - half)

    same = bool((ref.snapshot() == eng2.snapshot()).all())
    print(f"gen {eng2.generation}: resumed == uninterrupted: {same}, "
          f"population {eng2.population()}")
    if not same:
        raise SystemExit("resume diverged!")


if __name__ == "__main__":
    sys.exit(main())
