"""Distributed stepping over every visible device, with throughput.

Demonstrates the sharded fast paths on whatever mesh the machine offers:
the 2D-tiled SWAR runner, the (N, 1) row-band native-kernel runner, and —
the path a real v5e-8 takes by default — the SAME kernel on the 2D mesh
via flattened full-width bands (interpret mode off-TPU, Mosaic on TPU).
Run on the 8-virtual-device CPU rig to see the multi-chip code paths
without hardware:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/distributed_bands.py --side 512 --gens 64
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--side", type=int, default=512)
    ap.add_argument("--gens", type=int, default=64)
    ap.add_argument("--rule", default="B3/S23")
    args = ap.parse_args(argv)

    import jax

    from gameoflifewithactors_tpu import Engine
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

    n = len(jax.devices())
    grid = (np.random.default_rng(1)
            .integers(0, 2, size=(args.side, args.side), dtype=np.uint8))

    for shape, backend, label in (
            (mesh_lib.factor2d(n), "packed", "2D tiles / SWAR"),
            ((n, 1), "pallas", "row bands / native kernel"),
            (mesh_lib.factor2d(n), "pallas", "2D mesh / flattened bands")):
        m = mesh_lib.make_mesh(shape, jax.devices())
        eng = Engine(grid, args.rule, mesh=m, backend=backend,
                     gens_per_exchange=8 if backend == "pallas" else 1)
        eng.step(8)                      # compile + warm
        eng.block_until_ready()
        t0 = time.perf_counter()
        eng.step(args.gens)
        eng.block_until_ready()
        dt = time.perf_counter() - t0
        rate = args.side * args.side * args.gens / dt
        print(f"{label:28s} mesh {shape}: {rate:.3e} cell-updates/s  "
              f"(halo {eng.halo_bytes_per_gen()} B/gen, pop {eng.population()})")


if __name__ == "__main__":
    sys.exit(main())
