"""Losing a device shard mid-run and recovering bit-exactly.

The reference's fault story is Akka supervision restarting a crashed
CellActor — which silently loses that cell's state [SURVEY.md §6]. The
SPMD equivalent of a crashed actor is a lost device shard; the honest
recovery story is checkpoint-based replay. This example runs a soup on a
sharded engine under GuardedRun, zeroes one device's shard *in place*
mid-run (``fault.drop_shard`` — O(shard) host work, every other device
buffer untouched), shows the failure detector catching it at the next
checkpoint boundary, and verifies the replayed trajectory is bit-identical
to an unfaulted run.

    python examples/fault_recovery.py --side 128 --gens 48
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--side", type=int, default=128)
    ap.add_argument("--gens", type=int, default=48)
    ap.add_argument("--checkpoint-every", type=int, default=8)
    args = ap.parse_args(argv)

    import jax

    from gameoflifewithactors_tpu import Engine
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
    from gameoflifewithactors_tpu.utils import fault

    n = len(jax.devices())
    mesh = mesh_lib.make_mesh((n, 1), jax.devices())
    rng = np.random.default_rng(2026)
    grid = rng.integers(0, 2, size=(args.side, 2 * args.side),
                        dtype=np.uint8)

    # clean trajectory: the expected population at every checkpoint
    # boundary doubles as the failure detector (SPMD determinism makes the
    # redundant computation exact), and the final state is the oracle
    ref = Engine(grid, "B3/S23", mesh=mesh)
    expected = {0: ref.population()}
    for gen in range(args.checkpoint_every, args.gens + 1,
                     args.checkpoint_every):
        ref.step(args.checkpoint_every)
        expected[gen] = ref.population()

    eng = Engine(grid, "B3/S23", mesh=mesh)
    recoveries = []
    guard = fault.GuardedRun(
        eng, checkpoint_every=args.checkpoint_every,
        validator=lambda e: e.population() == expected.get(e.generation),
        on_recover=recoveries.append)

    half = args.gens // 2
    guard.run(half)
    victim = n // 2
    fault.drop_shard(eng, victim)
    print(f"gen {eng.generation}: dropped device shard {victim} of {n} "
          f"in flight (population now {eng.population()}, "
          f"expected {expected.get(eng.generation)})")

    guard.run(args.gens - half)
    print(f"gen {eng.generation}: recovered {guard.recoveries}x "
          f"(rolled back to gen {recoveries[0] if recoveries else '-'}), "
          f"population {eng.population()}")

    want = ref.snapshot()
    got = eng.snapshot()
    assert np.array_equal(got, want), "replayed trajectory diverged!"
    print("final state bit-identical to the unfaulted run")


if __name__ == "__main__":
    main()
