"""A million-cell 1D "long context" sharded over every device.

The elementary family's context-parallel runner splits one huge Wolfram
row over the mesh's column axis; each chunk moves ONE 32-cell halo word
per side and advances up to 32 generations locally (the corruption
light-cone creeps 1 cell/generation and the cropped halo word absorbs it
exactly). Rows on the mesh's row axis are independent universes — here we
run a small ensemble of rules over the same giant row.

    python examples/long_row.py --cells 1048576 --gens 256
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--cells", type=int, default=1 << 20)
    ap.add_argument("--gens", type=int, default=256)
    ap.add_argument("--rules", default="W30,W110,W184")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models.elementary import parse_elementary
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
    from gameoflifewithactors_tpu.parallel import sharded

    n = len(jax.devices())
    mesh = mesh_lib.make_mesh((1, n))
    g = 32
    chunks, rem = divmod(args.gens, g)
    if rem:
        chunks += 1  # round up: exact gen counts matter less than scale here

    rng = np.random.default_rng(1)
    row = rng.integers(0, 2, size=(1, args.cells), dtype=np.uint8)
    p = bitpack.pack(jnp.asarray(row))

    for name in args.rules.split(","):
        rule = parse_elementary(name)
        run = sharded.make_multi_step_elementary_sharded(
            mesh, rule, gens_per_exchange=g)
        out = run(mesh_lib.device_put_sharded_grid(p, mesh), chunks)
        pop = bitpack.population(out)   # uint64-exact even at 2^32+ cells
        print(f"{rule.notation:5s} {args.cells:>9d} cells x "
              f"{chunks * g:4d} gens over {n} devices  pop {pop}")


if __name__ == "__main__":
    sys.exit(main())
