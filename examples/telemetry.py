"""Run telemetry end to end: spans, compile events, watchdog, RunReport.

Drives a small soup through the coordinator/scheduler stack with a
telemetry session active, then shows all three pillars of obs/:

1. the per-phase host-time table (dispatch vs. sync vs. readback vs.
   subscriber time) and the jit compile events the first tick paid;
2. the stall watchdog flagging a deliberately wedged tick (a subscriber
   that sleeps past the deadline) and naming the last-completed span;
3. the RunReport JSON artifact plus a chrome://tracing span file —
   drop the latter into ui.perfetto.dev next to a ``jax.profiler``
   device trace for a combined host+device timeline.

    python examples/telemetry.py --side 256 --gens 64 --out /tmp/report.json
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--side", type=int, default=256)
    ap.add_argument("--gens", type=int, default=64)
    ap.add_argument("--ticks", type=int, default=8,
                    help="generations are run in this many ticks")
    ap.add_argument("--out", default="telemetry_report.json",
                    help="RunReport JSON path (a .trace.json chrome-trace "
                         "sibling is written next to it)")
    ap.add_argument("--stall-demo", action="store_true",
                    help="also wedge one tick past a 100 ms deadline to "
                         "show the watchdog diagnostic")
    args = ap.parse_args(argv)

    from gameoflifewithactors_tpu import GridCoordinator, TickScheduler
    from gameoflifewithactors_tpu.obs import TRACER, begin_run_telemetry

    # -- pillar 1+3: a normal measured run ----------------------------------
    telem = begin_run_telemetry(stall_deadline=60.0)
    coord = GridCoordinator((args.side, args.side), "B3/S23",
                            random_fill=0.5, track_population=True)
    telem.attach(coord)
    TickScheduler(coord, generations_per_tick=max(1, args.gens // args.ticks)
                  ).run(max_generations=args.gens)
    report = telem.finish(engine=coord.engine,
                          config={"example": "telemetry", "side": args.side,
                                  "gens": args.gens})

    # -- pillar 2: the watchdog catching a wedged tick ----------------------
    if args.stall_demo:
        from gameoflifewithactors_tpu.obs import StallWatchdog, arm, disarm

        stalls = []
        arm(StallWatchdog(0.1, on_stall=stalls.append))
        unsub = coord.subscribe(lambda frame: time.sleep(0.5))  # the wedge
        coord.tick(1)
        disarm()
        unsub()
        ev = stalls[0]
        print(f"watchdog: {ev.label} overran {ev.deadline_seconds:.1f}s "
              f"deadline; last completed span: {ev.last_completed_span}")

    path = report.save(args.out)
    trace_path = TRACER.write_chrome_trace(
        args.out.rsplit(".json", 1)[0] + ".trace.json")
    print("\n".join(report.summary_lines()))
    print(f"report written: {path}")
    print(f"host-span chrome trace written: {trace_path} "
          "(open in ui.perfetto.dev)", file=sys.stderr)


if __name__ == "__main__":
    main()
