"""Larger-than-Life zoo: box, diamond, and multi-state rules side by side.

Runs the same random soup under three LtL rules — Bosco (the classic
radius-5 box rule), a von Neumann diamond variant, and a Golly C>=3
multi-state rule whose failed survivors decay through dying states — and
prints a population/backends summary. Every rule resolves its own best
backend through the Engine's auto routing (bit-sliced packed for binary
rules on TPU; multi-state decay takes the bit-plane stack on CPU for
diamonds and box radius <= 3 — the measured crossover — and the byte
path otherwise).

    python examples/ltl_zoo.py --side 128 --gens 20
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--side", type=int, default=128)
    ap.add_argument("--gens", type=int, default=20)
    ap.add_argument("--fill", type=float, default=0.35)
    args = ap.parse_args(argv)

    import numpy as np

    from gameoflifewithactors_tpu import Engine

    rng = np.random.default_rng(0)
    soup = (rng.random((args.side, args.side)) < args.fill).astype(np.uint8)

    rules = [
        ("bosco", "Bosco / Bugs (R5 box, binary)"),
        ("R5,C0,M1,S34..58,B34..45,NN", "same intervals, diamond"),
        ("R2,C4,M1,S3..8,B5..9", "radius-2 box, 4 states (decay)"),
    ]
    for spec, label in rules:
        e = Engine(soup, spec)
        e.step(args.gens)
        print(f"{label:38s} backend={e.backend:6s} "
              f"gen {e.generation:4d}  pop {e.population()}")


if __name__ == "__main__":
    sys.exit(main())
