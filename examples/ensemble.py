"""Ensemble statistics: a batch of random universes stepped in parallel.

The reference runs ONE universe per actor system; the batched layer turns
the framework into an ensemble machine (SURVEY.md §3 DP row). This example
steps B random soups together — on a multi-device mesh each device owns a
slice of the batch — and reports the population trajectory's mean/spread,
the classic "soup settles to ~3% density" experiment.

    python examples/ensemble.py --batch 8 --side 256 --gens 200
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--side", type=int, default=256)
    ap.add_argument("--gens", type=int, default=200)
    ap.add_argument("--rule", default="B3/S23")
    ap.add_argument("--report-every", type=int, default=50)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from gameoflifewithactors_tpu.models.rules import parse_rule
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.ops._jit import tracked_jit
    from gameoflifewithactors_tpu.ops.packed import multi_step_packed
    from gameoflifewithactors_tpu.ops.stencil import Topology

    # this example batches the life-like SWAR path; parse_rule rejects
    # other families with a clear error instead of parse_any's pass-through
    rule = parse_rule(args.rule)
    rng = np.random.default_rng(0)
    grids = rng.integers(0, 2, size=(args.batch, args.side, args.side),
                         dtype=np.uint8)
    packed = jnp.stack([bitpack.pack(jnp.asarray(u)) for u in grids])

    # one program for the whole ensemble: vmap the multi-generation step,
    # jitted through the tracked entry point so compile events are attributed
    run = tracked_jit(jax.vmap(
        lambda p, n: multi_step_packed(p, n, rule=rule, topology=Topology.TORUS),
        in_axes=(0, None)), runner="examples.ensemble")

    cells = args.side * args.side
    done = 0
    while done < args.gens:
        n = min(args.report_every, args.gens - done)
        packed = run(packed, n)
        done += n
        pops = np.array([bitpack.population(packed[i])
                         for i in range(args.batch)]) / cells
        print(f"gen {done:5d}  density mean {pops.mean():.4f}  "
              f"min {pops.min():.4f}  max {pops.max():.4f}")


if __name__ == "__main__":
    sys.exit(main())
