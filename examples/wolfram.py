"""Elementary (Wolfram) 1D CA: print a spacetime diagram to the console.

Rule 90 from a single seed cell draws the Sierpinski triangle; rule 110
(Turing-complete) and rule 30 (chaos) are one flag away. The whole
evolution is computed on-device as one lax.scan over the packed row, then
shipped once for rendering.

    python examples/wolfram.py --rule W90 --width 128 --steps 48
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--rule", default="W90", help="W0..W255 (or rule<N>)")
    ap.add_argument("--width", type=int, default=128)
    ap.add_argument("--steps", type=int, default=48)
    ap.add_argument("--seed", default="center",
                    choices=["center", "random"])
    args = ap.parse_args(argv)

    import jax.numpy as jnp

    from gameoflifewithactors_tpu import (
        evolve_spacetime,
        pack,
        parse_elementary,
        unpack,
    )

    rule = parse_elementary(args.rule)
    row = np.zeros(args.width, dtype=np.uint8)
    if args.seed == "center":
        row[args.width // 2] = 1
    else:
        row[:] = np.random.default_rng(0).integers(0, 2, args.width)

    st = evolve_spacetime(pack(jnp.asarray(row[None])), args.steps, rule=rule)
    image = np.asarray(unpack(st[:, 0, :]))   # (steps+1, width), row = time
    for t, line in enumerate(image):
        print("".join(" #"[v] for v in line))
    print(f"{rule.notation}: {args.steps} generations of {args.width} cells")


if __name__ == "__main__":
    sys.exit(main())
