#!/usr/bin/env python
"""Fleet soak: N supervised coordinator processes under a seeded fault plan.

    python scripts/soak.py --seed 0 --duration 60

Launches a mixed fleet of worker subprocesses (resilience/worker.py —
packed, dense, sparse, LtL, ensemble specs), an unfaulted *oracle*
twin for each, and executes a deterministic
:class:`~gameoflifewithactors_tpu.resilience.FaultPlan`: state
corruption and drops through the supervisor's detected-fault channel,
induced stalls and retraces, and driver-side SIGKILL + ``--resume`` of
live workers. Throughout the run it scrapes each worker's ``/healthz``
(progress, restart counts) and ``/metrics`` (obs/exporter.py).

At the end it asserts the invariants the obs + resilience stack
promises, and writes ``soak_report.json``:

- every worker (faulted and oracle) exits 0 with ``ok: true`` — no
  circuit opened, no unexplained post-warm retrace (RetraceSentinel),
  no sanitizer trip (workers run under ``GOLTPU_SANITIZE=1``);
- the fleet injected the required fault-kind floor (state corruption,
  induced stall, worker SIGKILL);
- each faulted worker's final grid is bit-identical to its oracle's —
  recovery is exact, not approximate;
- every induced stall was detected by the watchdog and left a flight
  dump on disk;
- every killed worker resumed from its atomic checkpoint and still
  converged to the oracle grid;
- ``/metrics`` answered with ``goltpu_``-namespace content for every
  worker.

Exit 0 = all green. Same ``--seed`` replays the identical fault
schedule (the report embeds the plan JSON).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request
from pathlib import Path
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from gameoflifewithactors_tpu.obs import aggregate as obs_aggregate  # noqa: E402
from gameoflifewithactors_tpu.obs import flight as obs_flight  # noqa: E402
from gameoflifewithactors_tpu.obs import spans as obs_spans  # noqa: E402
from gameoflifewithactors_tpu.resilience.faultplan import (  # noqa: E402
    DRIVER_KINDS, STATE_KINDS, FaultPlan)

FLAVOR_ORDER = ("packed", "dense", "sparse", "ltl", "ensemble")
SHAPES = {"packed": (128, 128), "dense": (128, 128), "sparse": (128, 128),
          "ltl": (96, 96), "ensemble": (64, 64)}


def build_specs(args, out: Path, plan: FaultPlan) -> List[dict]:
    specs = []
    for w in range(args.processes):
        flavor = FLAVOR_ORDER[w % len(FLAVOR_ORDER)]
        specs.append({
            "name": f"w{w}-{flavor}",
            "flavor": flavor,
            "shape": list(SHAPES[flavor]),
            "rng_seed": args.seed * 1000 + w,
            "random_fill": 0.33,
            "generations": args.generations,
            "checkpoint_every": args.checkpoint_every,
            "watchdog_deadline": args.watchdog_deadline,
            "chunk_sleep_seconds": args.chunk_sleep,
            "workdir": str(out / f"w{w}"),
            "events": [e.to_dict() for e in plan.for_worker(w)
                       if e.kind not in DRIVER_KINDS],
        })
    return specs


class WorkerProc:
    """One worker subprocess + its scrape state."""

    def __init__(self, spec_path: Path, workdir: Path, env: dict,
                 resume: bool = False):
        self.workdir = workdir
        self.log = open(workdir / "worker.log", "ab")
        cmd = [sys.executable, "-m",
               "gameoflifewithactors_tpu.resilience.worker",
               "--spec", str(spec_path)]
        if resume:
            cmd.append("--resume")
        self.proc = subprocess.Popen(cmd, cwd=_REPO, env=env,
                                     stdout=subprocess.PIPE,
                                     stderr=self.log, text=True)
        self.port: Optional[int] = None
        self.resumed = resume
        self.last_health: dict = {}
        self.last_metrics: str = ""

    def read_port(self, timeout_s: float = 120.0) -> int:
        """First stdout line is ``METRICS_PORT <port>`` (printed before
        any stepping, but after the jax import the subprocess pays)."""
        t0 = time.perf_counter()
        line = self.proc.stdout.readline()
        if not line.startswith("METRICS_PORT"):
            raise RuntimeError(
                f"worker announced {line!r} instead of METRICS_PORT "
                f"(after {time.perf_counter() - t0:.0f}s)")
        self.port = int(line.split()[1])
        return self.port

    def scrape(self) -> None:
        if self.port is None or self.proc.poll() is not None:
            return
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{self.port}/healthz",
                    timeout=2) as r:
                self.last_health = json.loads(r.read())
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{self.port}/metrics",
                    timeout=2) as r:
                self.last_metrics = r.read().decode("utf-8")
        except (OSError, ValueError):
            pass  # mid-restart or mid-kill; the next poll retries

    def close(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
        self.log.close()


def run_fleet(args, out: Path, specs: List[dict], plan: FaultPlan,
              env: dict) -> dict:
    """Launch faulted workers + oracles, execute kills, wait, report."""
    deadline = time.perf_counter() + args.duration + args.grace
    kills = {e.worker: e for e in plan.events if e.kind == "kill"
             if specs[e.worker]["flavor"] != "ensemble"}
    killed: dict = {}

    workers: List[WorkerProc] = []
    oracles: List[WorkerProc] = []
    for spec in specs:
        wd = Path(spec["workdir"])
        wd.mkdir(parents=True, exist_ok=True)
        spec_path = wd / "spec.json"
        spec_path.write_text(json.dumps(spec, indent=2))
        workers.append(WorkerProc(spec_path, wd, env))
        # the oracle twin: identical spec, zero faults, no pacing
        ospec = dict(spec, events=[], chunk_sleep_seconds=0.0,
                     name=spec["name"] + "-oracle",
                     workdir=str(out / f"oracle-{spec['name']}"))
        owd = Path(ospec["workdir"])
        owd.mkdir(parents=True, exist_ok=True)
        ospec_path = owd / "spec.json"
        ospec_path.write_text(json.dumps(ospec, indent=2))
        oracles.append(WorkerProc(ospec_path, owd, env))
    for p in workers + oracles:
        p.read_port()

    # poll loop: scrape, time kills, resume killed workers
    while time.perf_counter() < deadline:
        alive = [p for p in workers + oracles if p.proc.poll() is None]
        if not alive:
            break
        for i, p in enumerate(workers):
            p.scrape()
            ev = kills.get(i)
            if (ev is not None and i not in killed
                    and p.proc.poll() is None
                    and p.last_health.get("generation", 0) >= ev.at_gen):
                os.kill(p.proc.pid, signal.SIGKILL)
                p.proc.wait()
                killed[i] = {"worker": i, "scheduled_at_gen": ev.at_gen,
                             "killed_at_gen": p.last_health["generation"]}
                obs_flight.note_event(
                    "driver_kill",
                    {"worker": i,
                     "at_gen": p.last_health["generation"]})
                print(f"soak: SIGKILL w{i} at generation "
                      f"{p.last_health['generation']} (scheduled "
                      f">= {ev.at_gen}); resuming", flush=True)
                old = p
                workers[i] = WorkerProc(
                    Path(specs[i]["workdir"]) / "spec.json",
                    Path(specs[i]["workdir"]), env, resume=True)
                workers[i].read_port()
                old.log.close()
        for p in oracles:
            p.scrape()
        time.sleep(args.poll_seconds)

    results = {"workers": [], "oracles": [], "killed": list(killed.values())}
    # last scraped exposition per process, for the fleet-wide merged
    # metrics artifact (popped out of the report before it is written)
    results["expositions"] = dict(
        {f"w{i}": p.last_metrics for i, p in enumerate(workers)},
        **{f"oracle{i}": p.last_metrics for i, p in enumerate(oracles)})
    for kind, procs in (("workers", workers), ("oracles", oracles)):
        for p in procs:
            rc = p.proc.poll()
            if rc is None:
                p.proc.kill()
                rc = "timeout"
            report_path = p.workdir / "report.json"
            report = (json.loads(report_path.read_text())
                      if report_path.exists() else None)
            results[kind].append({
                "workdir": str(p.workdir), "exit_code": rc,
                "report": report, "last_health": p.last_health,
                "scraped_metrics": bool(p.last_metrics),
                "metrics_has_namespace": "goltpu_" in p.last_metrics,
            })
            p.close()
    return results


def check_invariants(args, results: dict, specs: List[dict],
                     plan: FaultPlan) -> List[str]:
    """Every failed invariant becomes one human-readable line."""
    import numpy as np

    failures: List[str] = []

    def report_of(entry) -> dict:
        return entry.get("report") or {}

    for kind in ("workers", "oracles"):
        for entry in results[kind]:
            r = report_of(entry)
            if entry["exit_code"] != 0 or not r.get("ok"):
                failures.append(
                    f"{entry['workdir']}: exit={entry['exit_code']} "
                    f"ok={r.get('ok')} error={r.get('error')}")

    # fault-kind floor: state corruption + stall from worker reports,
    # SIGKILL from the driver's own accounting
    applied = [f for entry in results["workers"]
               for m in report_of(entry).get("members", [])
               for f in m.get("faults_applied", [])]
    applied_kinds = {f["kind"] for f in applied}
    if not applied_kinds & set(STATE_KINDS):
        failures.append(f"no state-corruption fault applied ({applied_kinds})")
    if "stall" not in applied_kinds:
        failures.append(f"no stall fault applied ({applied_kinds})")
    if plan_kills(plan, specs) and not results["killed"]:
        failures.append("kill was scheduled but never executed "
                        "(workers finished between polls?)")

    for i, (w, o) in enumerate(zip(results["workers"], results["oracles"])):
        wf = Path(w["workdir"]) / "final.npy"
        of = Path(o["workdir"]) / "final.npy"
        if not (wf.exists() and of.exists()):
            failures.append(f"w{i}: missing final grid "
                            f"({wf.exists()=} {of.exists()=})")
            continue
        if not np.array_equal(np.load(wf), np.load(of)):
            failures.append(
                f"w{i}: faulted-and-recovered final grid differs from "
                f"oracle ({specs[i]['flavor']})")

    for i, entry in enumerate(results["workers"]):
        r = report_of(entry)
        stalls_injected = sum(
            1 for m in r.get("members", [])
            for f in m.get("faults_applied", []) if f["kind"] == "stall")
        if stalls_injected:
            if r.get("stalls_detected", 0) < stalls_injected:
                failures.append(
                    f"w{i}: {stalls_injected} stalls injected but only "
                    f"{r.get('stalls_detected', 0)} detected")
            if not (Path(entry["workdir"]) / "flight.jsonl").exists():
                failures.append(f"w{i}: induced stall left no flight dump")
            elif r.get("flight_dumps", 0) < 1:
                failures.append(f"w{i}: flight recorder never dumped "
                                f"despite {stalls_injected} stalls")
        retraces = sum(
            1 for m in r.get("members", [])
            for f in m.get("faults_applied", []) if f["kind"] == "retrace")
        attributed = sum(m.get("supervisor", {}).get(
            "retraces_attributed", 0) for m in r.get("members", []))
        if retraces and attributed < retraces:
            failures.append(f"w{i}: {retraces} retraces injected, "
                            f"{attributed} attributed")
        if not entry["metrics_has_namespace"]:
            failures.append(f"w{i}: /metrics never served goltpu_ content")

    for k in results["killed"]:
        r = report_of(results["workers"][k["worker"]])
        if not r.get("resume"):
            failures.append(
                f"w{k['worker']}: killed but final report says it never "
                "resumed from checkpoint")
    return failures


def plan_kills(plan: FaultPlan, specs: List[dict]) -> List[int]:
    return [e.worker for e in plan.events if e.kind == "kill"
            and specs[e.worker]["flavor"] != "ensemble"]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="fleet soak under a deterministic fault plan")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--duration", type=float, default=60.0,
                        help="soft wall-clock budget (seconds); workers "
                        "exceeding it + grace are killed and failed")
    parser.add_argument("--processes", type=int, default=3)
    parser.add_argument("--generations", type=int, default=240)
    parser.add_argument("--checkpoint-every", type=int, default=40)
    parser.add_argument("--faults-per-worker", type=int, default=2)
    parser.add_argument("--kills", type=int, default=1)
    parser.add_argument("--watchdog-deadline", type=float, default=6.0)
    parser.add_argument("--chunk-sleep", type=float, default=0.3)
    parser.add_argument("--poll-seconds", type=float, default=0.1)
    parser.add_argument("--grace", type=float, default=120.0,
                        help="extra seconds past --duration before the "
                        "driver declares a hang")
    parser.add_argument("--out", default=None,
                        help="output dir (default: ./soak_out)")
    parser.add_argument("--tpu", action="store_true",
                        help="do not force JAX_PLATFORMS=cpu in workers")
    args = parser.parse_args(argv)

    out = Path(args.out or os.path.join(_REPO, "soak_out"))
    out.mkdir(parents=True, exist_ok=True)

    # kills target the first --kills non-ensemble workers, so resume is
    # exercised on a single-member checkpoint
    kill_targets = [w for w in range(args.processes)
                    if FLAVOR_ORDER[w % len(FLAVOR_ORDER)] != "ensemble"
                    ][:args.kills]
    plan = FaultPlan.generate(
        args.seed, workers=args.processes, horizon=args.generations,
        faults_per_worker=args.faults_per_worker,
        kinds=("corrupt_region", "drop_region", "drop_shard", "stall",
               "retrace"),
        ensure_kinds=("corrupt_region", "stall", "retrace"),
        kill_workers=kill_targets)
    (out / "faultplan.json").write_text(plan.to_json())
    print(f"soak: seed={args.seed} processes={args.processes} "
          f"plan kinds={plan.kinds()} "
          f"({len(plan.events)} events)", flush=True)

    specs = build_specs(args, out, plan)
    env = dict(os.environ, GOLTPU_SANITIZE="1",
               GOLTPU_CACHE_DIR=os.environ.get(
                   "GOLTPU_CACHE_DIR",
                   os.path.join(_REPO, ".goltpu_cache")))
    if not args.tpu:
        env["JAX_PLATFORMS"] = "cpu"

    # fleet trace: workers inherit the driver's trace id + span id via
    # GOLTPU_TRACE, so their spans nest under the driver on the merged
    # timeline; the driver tapes its own kills into driver-flight.jsonl
    ctx = obs_spans.TraceContext(obs_spans.new_trace_id(),
                                 obs_spans.new_span_id())
    obs_spans.set_process_context(ctx)
    env.update(ctx.child_env())
    fr = obs_flight.FlightRecorder(str(out / "driver-flight.jsonl"))
    fr.install(signals=False)
    obs_flight.arm(fr)

    t0 = time.perf_counter()
    with obs_spans.span("soak.fleet", seed=args.seed,
                        processes=args.processes):
        results = run_fleet(args, out, specs, plan, env)
    wall = time.perf_counter() - t0
    failures = check_invariants(args, results, specs, plan)

    expositions = results.pop("expositions", {})
    live = {k: v for k, v in expositions.items() if v}
    if live:
        (out / "fleet_metrics.prom").write_text(
            obs_aggregate.merge_expositions(live))
    fr.dump(f"soak driver done (failures={len(failures)})")
    obs_flight.disarm()
    dumps = sorted(out.glob("*/flight.jsonl"))
    dumps.append(out / "driver-flight.jsonl")
    dumps = [p for p in dumps if p.exists()]
    obs_aggregate.write_merged_timeline(
        str(out / "timeline.json"),
        flight_dumps=[str(p) for p in dumps],
        labels={str(p): (p.parent.name if p.name == "flight.jsonl"
                         else "driver") for p in dumps})

    report = {
        "seed": args.seed,
        "trace_id": ctx.trace_id,
        "timeline": str(out / "timeline.json"),
        "fleet_metrics": (str(out / "fleet_metrics.prom")
                          if live else None),
        "plan": json.loads(plan.to_json()),
        "wall_seconds": round(wall, 2),
        "results": results,
        "invariant_failures": failures,
        "ok": not failures,
    }
    (out / "soak_report.json").write_text(json.dumps(report, indent=2))
    if failures:
        print(f"soak: FAILED after {wall:.1f}s "
              f"({len(failures)} invariant failures):", flush=True)
        for f in failures:
            print(f"  - {f}", flush=True)
        return 1
    print(f"soak: OK in {wall:.1f}s — {args.processes} workers, "
          f"{len(plan.events)} scheduled faults "
          f"({', '.join(plan.kinds())}), {len(results['killed'])} "
          "kill/resume cycles, all grids bit-identical to oracle",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
