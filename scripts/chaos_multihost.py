#!/usr/bin/env python
"""Multi-host chaos drill: SIGKILL, preemption, and checkpoint rot on a
live localhost fleet — recovery must be bit-exact.

    python scripts/chaos_multihost.py --seed 0

Launches an :class:`~gameoflifewithactors_tpu.resilience.distributed.
ElasticFleet` of N real OS processes (multi-controller JAX over
localhost, sharded v2 checkpoints) and executes a seeded
:class:`FaultPlan` of the *driver-level* fault kinds. By default the
fleet runs the width-k ghost-zone pipeline on a 2x2 device mesh
(``--mesh 2x2 --gens-per-exchange 4`` — one halo exchange per 4
generations); shrunk epochs deterministically re-tile via
``parallel.multihost.global_mesh_for_grid``. ``--mesh band
--gens-per-exchange 1`` restores the legacy lock-step row-band drill.
The fault kinds:

- ``process_kill`` — SIGKILL a worker mid-run; every survivor must
  self-detect the dead peer (stale heartbeat / barrier deadline) and
  exit within the detection bound instead of wedging in a collective;
- ``process_preempt`` — SIGTERM a worker; it must finish its chunk,
  checkpoint, and exit with the distinct "preempted" status, and the
  fleet must re-form *smaller* (the mesh reshapes over n-1 processes);
- ``checkpoint_corrupt`` — flip bytes in a shard of the newest
  committed checkpoint generation (then kill its owner); the rebuilt
  fleet's restore must refuse the corrupt generation by CRC and fall
  back to the previous complete one.

After the fleet converges, the script replays the same spec on a
single device (``ops/packed.multi_step_packed`` — no fleet, no faults)
and asserts the fleet's final grid is **bit-identical** to the
oracle's: elastic recovery is exact replay, not approximation. It also
asserts the paper trail: detection latency under the bound, a
"preempted" status + shrunk roster, a refused generation in some
worker's restore record, survivor flight dumps on disk, and the driver
registry's recovery-latency histogram populated.

Writes ``<out>/chaos_report.json`` (fleet report + oracle verdict +
per-check results). Exit 0 = all green. Same ``--seed`` replays the
identical fault schedule.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import axon_guard  # noqa: E402

from gameoflifewithactors_tpu.resilience.faultplan import (  # noqa: E402
    FaultEvent, FaultPlan)


def build_events(seed: int, workers: int, horizon: int) -> List[FaultEvent]:
    """The drill's schedule: one event of each driver kind, at seeded
    generations, targets clamped to workers 0/1 so every event stays
    addressable after the preemption shrinks the roster."""
    plan = FaultPlan.generate(
        seed, workers=2, horizon=horizon, faults_per_worker=0,
        kinds=("process_kill", "process_preempt", "checkpoint_corrupt"),
        ensure_kinds=("process_kill", "process_preempt",
                      "checkpoint_corrupt"))
    assert workers >= 3, "drill needs >= 3 processes to survive a shrink"
    return list(plan.events)


def _parse_pair(text: str, what: str) -> tuple:
    try:
        a, b = text.lower().split("x")
        return int(a), int(b)
    except ValueError:
        raise SystemExit(f"--{what} wants AxB (e.g. 96x128), got {text!r}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="chaos drill for the elastic multi-host runtime")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--processes", type=int, default=4)
    parser.add_argument("--generations", type=int, default=120)
    parser.add_argument("--chunk", type=int, default=20)
    parser.add_argument("--shape", default="96x128",
                        help="grid HxW (default 96x128: 4 packed word "
                        "columns, enough for a 2x2 mesh of ghost tiles)")
    parser.add_argument("--mesh", default="2x2",
                        help="device mesh NXxNY, or 'band' for legacy "
                        "(n, 1) row bands")
    parser.add_argument("--gens-per-exchange", type=int, default=4,
                        help="halo exchange cadence k of the ghost-zone "
                        "pipeline; 1 = lock-step per-gen exchange")
    parser.add_argument("--chunk-sleep", type=float, default=0.3,
                        help="pacing so faults land mid-run")
    parser.add_argument("--heartbeat-deadline", type=float, default=3.0)
    parser.add_argument("--barrier-deadline", type=float, default=15.0)
    parser.add_argument("--out", default="chaos_out")
    args = parser.parse_args(argv)

    from gameoflifewithactors_tpu.resilience.distributed import (
        EXIT_PREEMPTED, ElasticFleet, ElasticSpec, initial_grid)

    mesh_shape = (None if args.mesh in ("band", "none")
                  else _parse_pair(args.mesh, "mesh"))
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    spec = ElasticSpec(
        shape=_parse_pair(args.shape, "shape"),
        target_gens=args.generations, chunk=args.chunk,
        rng_seed=args.seed,
        mesh_shape=mesh_shape,
        gens_per_exchange=args.gens_per_exchange,
        chunk_sleep_seconds=args.chunk_sleep,
        heartbeat_deadline_seconds=args.heartbeat_deadline,
        barrier_deadline_seconds=args.barrier_deadline)
    events = build_events(args.seed, args.processes, args.generations)
    print(f"chaos plan (seed {args.seed}): "
          + ", ".join(f"{e.kind}@gen{e.at_gen}->w{e.worker}"
                      for e in events), flush=True)

    from gameoflifewithactors_tpu.obs import aggregate as obs_aggregate
    from gameoflifewithactors_tpu.obs import exporter as obs_exporter
    from gameoflifewithactors_tpu.obs import flight as obs_flight
    from gameoflifewithactors_tpu.obs.registry import REGISTRY

    env = {**os.environ}
    env["PYTHONPATH"] = axon_guard.strip_pythonpath()
    env["GOLTPU_SANITIZE"] = env.get("GOLTPU_SANITIZE", "1")
    # driver tape: armed before the fleet so _fire's kill/preempt/corrupt
    # events land on it and show up on the merged fleet timeline
    fr = obs_flight.FlightRecorder(str(out / "driver-flight.jsonl"))
    fr.install(signals=False)
    obs_flight.arm(fr)
    fleet = ElasticFleet(out, spec, num_processes=args.processes, env=env)
    report = fleet.run(events)
    fr.dump("chaos driver done")
    obs_flight.disarm()

    # -- the oracle: same spec, one device, zero faults -----------------------
    jax = axon_guard.force_cpu(1)
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models.generations import parse_any
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.ops.packed import multi_step_packed
    from gameoflifewithactors_tpu.ops.stencil import Topology

    packed0 = jnp.asarray(bitpack.pack_np(initial_grid(spec)))
    oracle = bitpack.unpack_np(np.asarray(multi_step_packed(
        packed0, spec.target_gens, rule=parse_any(spec.rule),
        topology=Topology(spec.topology))))[:, :spec.shape[1]]

    checks: List[tuple] = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        checks.append((name, bool(ok), detail))
        print(f"  {'PASS' if ok else 'FAIL'}  {name}"
              + (f"  ({detail})" if detail else ""), flush=True)

    print("chaos drill verdicts:", flush=True)
    check("fleet converged", report["ok"],
          f"{len(report['epochs'])} epochs")
    fired = {f["kind"] for f in report["faults_fired"]}
    check("all fault kinds fired",
          fired == {"process_kill", "process_preempt", "checkpoint_corrupt"},
          f"fired: {sorted(fired)}")

    # detection: after every fault, all survivors exited in bounded time
    bound = (spec.heartbeat_deadline_seconds
             + spec.barrier_deadline_seconds + 20.0)
    detections = [(e["epoch"], e["detection_seconds"])
                  for e in report["epochs"] if "detection_seconds" in e]
    check("peer loss detected within deadline",
          len(detections) == len(report["faults_fired"])
          and all(d <= bound for _, d in detections),
          f"{detections} (bound {bound:.0f}s)")

    # preemption: distinct exit status, then a smaller fleet
    pre_epochs = [e for e in report["epochs"]
                  if EXIT_PREEMPTED in (e.get("exit_codes") or [])]
    shrank = any(
        later["num_processes"] < e["num_processes"]
        for e in pre_epochs
        for later in report["epochs"][e["epoch"] + 1:])
    check("preempted worker exited 17 and fleet re-formed smaller",
          bool(pre_epochs) and shrank,
          f"rosters: {[e['num_processes'] for e in report['epochs']]}")
    statuses = [s for e in report["epochs"]
                for s in (e.get("statuses") or []) if s]
    check("preempted status published",
          any(s["status"] == "preempted" for s in statuses))

    # checkpoint rot: some epoch's restore refused a generation by CRC
    refused = []
    for rec in sorted((out / "restore").glob("e*-p*.json")):
        for d, why in json.loads(rec.read_text()).get("skipped", []):
            refused.append((rec.name, d, why))
    check("corrupt generation refused at restore, older one used",
          any("CRC32" in why or "unreadable" in why
              for _rec, _d, why in refused),
          f"{len(refused)} refusals")

    # the 2D ghost-zone pipeline really was the compute core: some
    # epoch's restore records must show the requested mesh with the
    # ghost runner (shrunk epochs legitimately re-tile to other shapes)
    if mesh_shape is not None and args.gens_per_exchange > 1:
        recs = [json.loads(p.read_text())
                for p in sorted((out / "restore").glob("e*-p*.json"))]
        check("ghost pipeline ran on the requested 2D mesh",
              any(r.get("mesh") == list(mesh_shape)
                  and r.get("runner") == "ghost" for r in recs),
              f"meshes {sorted({tuple(r.get('mesh', [])) for r in recs})}")

    # paper trail: survivors dumped flight tapes; recovery latency landed
    dumps = list((out / "flight").glob("*.jsonl"))
    check("survivor flight dumps on disk", len(dumps) > 0,
          f"{len(dumps)} dumps")
    recov = report["registry"].get("elastic_recovery_seconds", {})
    n_recov = sum(s["n"] for s in recov.get("series", []))
    check("recovery latency histogram populated",
          n_recov >= len(report["faults_fired"]),
          f"{n_recov} observations")

    # -- the merged fleet timeline: ONE clock-aligned chrome trace ------------
    tapes = sorted((out / "flight").glob("*.jsonl"))
    tapes.append(out / "driver-flight.jsonl")
    timeline_path = obs_aggregate.write_merged_timeline(
        str(out / "timeline.json"),
        flight_dumps=[str(p) for p in tapes if p.exists()])
    timeline = json.loads(Path(timeline_path).read_text())
    problems = obs_aggregate.validate_timeline(timeline)
    check("merged timeline clock-aligned", not problems
          and not timeline.get("unaligned"),
          f"{len(problems)} problems, "
          f"{len(timeline.get('unaligned', []))} unaligned")
    ranks = {int(lbl.rsplit("-p", 1)[1])
             for lbl in timeline.get("flight_headers", {}) if "-p" in lbl}
    check("timeline has tapes from every worker rank plus the driver",
          ranks == set(range(args.processes))
          and "driver-flight" in timeline.get("flight_headers", {}),
          f"ranks {sorted(ranks)}")
    span_tids = {ev.get("args", {}).get("trace_id")
                 for ev in timeline["traceEvents"]
                 if ev.get("ph") == "X"}
    check("worker and driver spans share the fleet trace id",
          span_tids == {report["trace_id"]},
          f"{len(span_tids)} distinct trace ids in spans")
    fault_kinds = {(ev.get("args") or {}).get("fault")
                   for ev in timeline["traceEvents"]
                   if ev.get("name") == "driver_fault"}
    check("kill/preempt/corrupt events visible on the timeline",
          {"process_kill", "process_preempt",
           "checkpoint_corrupt"} <= fault_kinds,
          f"saw {sorted(k for k in fault_kinds if k)}")
    # aggregated driver metrics, proc-labeled like a fleet scrape
    (out / "fleet_metrics.prom").write_text(
        obs_aggregate.merge_expositions(
            {"driver": obs_exporter.render_prometheus(REGISTRY.snapshot())}))

    # the one that matters: bit-identical to the unfaulted oracle
    final_path = report.get("final_grid")
    if final_path:
        final = np.load(final_path)
        identical = final.shape == oracle.shape and (final == oracle).all()
        check("final grid bit-identical to single-device oracle", identical,
              f"popcount fleet={int(final.sum())} oracle={int(oracle.sum())}")
    else:
        check("final grid bit-identical to single-device oracle", False,
              "no final grid written")

    ok = all(c[1] for c in checks)
    report["oracle"] = {"checks": [
        {"name": n, "ok": o, "detail": d} for n, o, d in checks]}
    report["ok_with_oracle"] = ok
    tmp = out / f"chaos_report.json.tmp{os.getpid()}"
    tmp.write_text(json.dumps(report, indent=2))
    os.replace(tmp, out / "chaos_report.json")
    print(("CHAOS-MULTIHOST-OK" if ok else "CHAOS-MULTIHOST-FAILED")
          + f" report={out / 'chaos_report.json'}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
