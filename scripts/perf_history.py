"""Perf history: fold loose bench artifacts into one queryable curve.

The repo's performance record is scattered — ``BENCH_r*.json`` driver
wrappers at the root, bench records / weak-scaling records / RunReports
under ``results/`` — and only a human rereading files sees the
trajectory. This script folds them all into an **append-only**
``results/history.jsonl`` time-series (one JSON object per line, deduped
by content fingerprint so re-running never duplicates), then prints a
markdown trend table per metric series with **median/MAD anomaly
detection**: an entry more than 3.5 robust standard deviations from its
series median is flagged (with a 30%-of-median fallback when the MAD
collapses to zero — a series of identical values plus one outlier).

Shapes folded (the same ones scripts/perf_gate.py accepts):
- ``BENCH_r*.json``: driver wrappers, measurement under ``"parsed"``;
- ``results/*.json`` bench records (``{"metric", "value", ...}``),
  including weak-scaling records;
- ``results/tpu_best.json`` / ``tpu_worklist.json`` stores (one entry
  per persisted key);
- RunReports (``step_metrics``): best cell-updates/sec per report file.

Usage:
  python scripts/perf_history.py                    # fold + append + trend
  python scripts/perf_history.py --check            # read-only anomaly scan
  python scripts/perf_history.py --check --strict   # exit 1 on anomaly
  python scripts/perf_history.py --markdown TREND.md

Exit codes: 0 = ok (``--check`` without ``--strict`` is informational —
anomalies print but never block, CI's warm-up mode), 1 = ``--strict``
and anomalies found, 2 = unusable input. Stdlib only, no jax, no
package import — history must be writable while a TPU tunnel is wedged.
"""

from __future__ import annotations

import argparse
import glob
import hashlib
import json
import os
import sys
import time
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: |robust z| above this flags an anomaly (0.6745 * (x - median) / MAD).
ANOMALY_Z = 3.5
#: MAD == 0 fallback: relative deviation from the median above this flags.
ANOMALY_REL = 0.30
#: A series needs at least this many entries before anomalies are called
#: (a 2-point series has no notion of "typical").
MIN_SERIES = 4


# -- entry extraction ---------------------------------------------------------


def _entry(series: str, value, unit=None, recorded_at=None, commit=None,
           stale=None, source: str = "?") -> Optional[dict]:
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        return None
    e = {"series": series, "value": float(value), "source": source}
    if unit:
        e["unit"] = unit
    if recorded_at:
        e["recorded_at"] = recorded_at
    if commit:
        e["commit"] = commit
    if stale:
        e["stale"] = True
    e["id"] = hashlib.sha1(
        f"{series}|{e['value']!r}|{recorded_at or ''}|{source}"
        .encode()).hexdigest()[:16]
    return e


def _from_bench_record(rec: dict, source: str) -> List[dict]:
    out = []
    e = _entry(rec["metric"], rec.get("value"), rec.get("unit"),
               rec.get("recorded_at"), rec.get("commit"),
               rec.get("stale") or rec.get("needs_recapture"), source)
    if e:
        out.append(e)
    sceq = rec.get("single_chip_equivalent_updates_per_sec")
    e = _entry(f"{rec['metric']} [per-chip-equivalent]", sceq,
               rec.get("unit"), rec.get("recorded_at"), rec.get("commit"),
               rec.get("stale"), source)
    if e:
        out.append(e)
    return out


def _from_run_report(rec: dict, source: str) -> List[dict]:
    rates = [m.get("cell_updates_per_sec")
             for m in rec.get("step_metrics") or []
             if isinstance(m, dict)
             and isinstance(m.get("cell_updates_per_sec"), (int, float))]
    if not rates:
        return []
    stem = os.path.splitext(os.path.basename(source))[0]
    e = _entry(f"report/{stem}/best_cell_updates_per_sec", max(rates),
               "cell-updates/sec", rec.get("created_at"), None, None, source)
    return [e] if e else []


def extract_entries(rec, source: str) -> List[dict]:
    """History entries from one loaded JSON artifact (any known shape);
    [] for shapes with nothing to track (manifests, logs)."""
    if not isinstance(rec, dict):
        return []
    if isinstance(rec.get("parsed"), dict) and "metric" not in rec:
        rec = rec["parsed"]        # BENCH_rNN driver wrapper
    if "metric" in rec and "value" in rec:
        return _from_bench_record(rec, source)
    if isinstance(rec.get("step_metrics"), list):
        return _from_run_report(rec, source)
    # a store (tpu_best.json / tpu_worklist.json): key -> record
    out: List[dict] = []
    for key, sub in rec.items():
        if isinstance(sub, dict) and "metric" in sub and "value" in sub:
            out.extend(_from_bench_record(sub, f"{source}#{key}"))
    return out


def scan_repo(repo: str) -> List[dict]:
    """All history entries extractable from the repo's committed perf
    artifacts (BENCH_*.json + results/*.json), unreadable files skipped
    with a stderr note — one bad artifact must not hide the rest."""
    entries: List[dict] = []
    paths = sorted(glob.glob(os.path.join(repo, "BENCH_*.json")))
    paths += sorted(glob.glob(os.path.join(repo, "results", "*.json")))
    for path in paths:
        rel = os.path.relpath(path, repo)
        if rel.endswith("history.jsonl"):
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"perf_history: skipping {rel}: {exc}", file=sys.stderr)
            continue
        entries.extend(extract_entries(rec, rel))
    return entries


# -- the append-only history file ---------------------------------------------


def load_history(path: str) -> List[dict]:
    entries = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # a torn tail line must not kill the scan
                if isinstance(rec, dict) and "series" in rec:
                    entries.append(rec)
    except OSError:
        pass
    return entries


def fold(repo: str, history_path: str, *, write: bool = True) -> dict:
    """Merge fresh repo entries into the history. Append-only: existing
    lines are never rewritten; new entries (by fingerprint) are appended
    with an ``appended_at`` stamp. Returns {"history", "appended"}."""
    history = load_history(history_path)
    seen = {e.get("id") for e in history}
    fresh = [e for e in scan_repo(repo) if e["id"] not in seen]
    # dedupe within the scan too (tpu_best and a BENCH wrapper can carry
    # the identical measurement)
    uniq: Dict[str, dict] = {}
    for e in fresh:
        uniq.setdefault(e["id"], e)
    fresh = list(uniq.values())
    if fresh:
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        for e in fresh:
            e["appended_at"] = stamp
        if write:
            os.makedirs(os.path.dirname(history_path) or ".", exist_ok=True)
            with open(history_path, "a") as f:
                for e in fresh:
                    f.write(json.dumps(e, sort_keys=True) + "\n")
    return {"history": history + fresh, "appended": fresh}


# -- median/MAD anomaly detection ---------------------------------------------


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def series_stats(entries: List[dict]) -> Dict[str, dict]:
    """Per-series robust stats + anomaly flags, entries in recorded
    order (recorded_at when present, else file order)."""
    by_series: Dict[str, List[dict]] = {}
    for e in entries:
        by_series.setdefault(e["series"], []).append(e)
    out: Dict[str, dict] = {}
    for series, es in by_series.items():
        es = sorted(es, key=lambda e: e.get("recorded_at") or "")
        values = [e["value"] for e in es]
        med = _median(values)
        mad = _median([abs(v - med) for v in values])
        anomalies = []
        if len(values) >= MIN_SERIES:
            for e in es:
                dev = abs(e["value"] - med)
                if mad > 0:
                    z = 0.6745 * dev / mad
                    if z > ANOMALY_Z:
                        anomalies.append({**e, "robust_z": round(z, 2)})
                elif med != 0 and dev / abs(med) > ANOMALY_REL:
                    anomalies.append({**e, "rel_dev": round(dev / abs(med), 3)})
        out[series] = {
            "count": len(values),
            "min": min(values), "median": med, "max": max(values),
            "mad": mad,
            "latest": values[-1],
            "latest_vs_median": (values[-1] / med) if med else None,
            "anomalies": anomalies,
        }
    return out


def trend_table(stats: Dict[str, dict]) -> List[str]:
    """The markdown trend table — the queryable face of the curve."""
    lines = ["| series | n | min | median | max | latest | vs median | flags |",
             "|---|---|---|---|---|---|---|---|"]

    def g(v):
        return f"{v:.4g}" if isinstance(v, (int, float)) else "-"

    for series in sorted(stats):
        s = stats[series]
        vs = (f"{s['latest_vs_median']:.2f}x"
              if s["latest_vs_median"] is not None else "-")
        flags = (f"{len(s['anomalies'])} anomaly(ies)"
                 if s["anomalies"] else "")
        lines.append(
            f"| {series} | {s['count']} | {g(s['min'])} | {g(s['median'])} "
            f"| {g(s['max'])} | {g(s['latest'])} | {vs} | {flags} |")
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--repo", default=_REPO,
                    help="repo root to scan (default: this checkout)")
    ap.add_argument("--history", default=None, metavar="PATH",
                    help="history file (default <repo>/results/history.jsonl)")
    ap.add_argument("--check", action="store_true",
                    help="read-only: scan + report anomalies, write nothing")
    ap.add_argument("--strict", action="store_true",
                    help="with --check: exit 1 when anomalies are found "
                         "(default is informational — report, don't block)")
    ap.add_argument("--markdown", default=None, metavar="PATH",
                    help="also write the trend table to PATH")
    ap.add_argument("--json", action="store_true",
                    help="emit the stats as one JSON object")
    args = ap.parse_args(argv)

    repo = os.path.abspath(args.repo)
    if not os.path.isdir(repo):
        print(f"perf_history: not a directory: {repo}", file=sys.stderr)
        return 2
    history_path = args.history or os.path.join(
        repo, "results", "history.jsonl")

    folded = fold(repo, history_path, write=not args.check)
    entries = folded["history"]
    if not entries:
        print("perf_history: no perf artifacts found — nothing to fold",
              file=sys.stderr)
        return 2
    stats = series_stats(entries)
    n_anom = sum(len(s["anomalies"]) for s in stats.values())

    table = trend_table(stats)
    if args.json:
        print(json.dumps({
            "perf_history": True,
            "history": history_path,
            "entries": len(entries),
            "appended": len(folded["appended"]),
            "series": stats,
            "anomalies": n_anom,
        }, indent=1, sort_keys=True))
    else:
        print("\n".join(table))
        for series in sorted(stats):
            for a in stats[series]["anomalies"]:
                why = (f"robust z {a['robust_z']}" if "robust_z" in a
                       else f"{a['rel_dev']:.0%} off median")
                print(f"ANOMALY: {series} = {a['value']:.4g} "
                      f"({why}; {a.get('source', '?')})")
        verb = "would append" if args.check else "appended"
        print(f"perf_history: {len(entries)} entr(ies) across "
              f"{len(stats)} series, {verb} {len(folded['appended'])}, "
              f"{n_anom} anomal(ies)")
    if args.markdown:
        with open(args.markdown, "w") as f:
            f.write("\n".join(table) + "\n")
    if args.strict and n_anom:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
