#!/usr/bin/env python
"""goltpu-lint CLI: the TPU-invariant static-analysis gate.

    python scripts/lint.py gameoflifewithactors_tpu scripts

Exit codes (the CI contract, pinned in tests/test_lint.py):

    0  clean — no unsuppressed findings
    1  unsuppressed findings (or stale baseline entries with --strict-baseline)
    2  bad input — missing path, unparseable file, broken baseline

Runs with **no jax installed** (the engine is pure stdlib AST), so CI
lints before — and far faster than — the test install. ``--json`` emits
the machine-readable result for tooling; ``--write-baseline`` refreshes
the grandfather file (this repo keeps it empty — new findings are fixed
or pragma'd with a reason, not baselined).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lint_lib():
    """Load analysis/lint.py WITHOUT the package __init__ (which imports
    jax): a synthetic parent package keeps the `from . import rules`
    registration import working — same standalone idiom as perf_gate.py,
    so linting works on a jax-less CI box or while a tunnel is wedged."""
    import importlib.util
    import types

    pkg_dir = os.path.join(_REPO, "gameoflifewithactors_tpu", "analysis")
    pkg_name = "goltpu_lint_standalone"
    if pkg_name not in sys.modules:
        pkg = types.ModuleType(pkg_name)
        pkg.__path__ = [pkg_dir]
        sys.modules[pkg_name] = pkg
    mod_name = pkg_name + ".lint"
    if mod_name in sys.modules:
        return sys.modules[mod_name]
    spec = importlib.util.spec_from_file_location(
        mod_name, os.path.join(pkg_dir, "lint.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[mod_name] = mod
    spec.loader.exec_module(mod)
    return mod


lint_lib = _load_lint_lib()

DEFAULT_BASELINE = os.path.join(_REPO, "lint_baseline.json")


def _sarif_payload(result) -> dict:
    """Minimal SARIF 2.1.0 document: one run, the full rule registry in
    the tool descriptor, one result per unsuppressed finding. Stays
    stdlib-only like everything else in this file — the CI lint job
    uploads this with no jax (and no SARIF library) installed."""
    registry = dict(lint_lib.RULES)
    registry.update(getattr(lint_lib, "PROJECT_RULES", {}))
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "goltpu-lint",
                "informationUri":
                    "https://github.com/gameoflifewithactors_tpu"
                    "#static-analysis--sanitizers",
                "rules": [
                    {"id": code, "name": rule.name,
                     "shortDescription": {"text": rule.summary}}
                    for code, rule in sorted(registry.items())],
            }},
            "results": [
                {"ruleId": f.code, "level": "error",
                 "message": {"text": f.message},
                 "locations": [{"physicalLocation": {
                     "artifactLocation": {
                         "uri": f.path.replace(os.sep, "/")},
                     "region": {"startLine": f.line,
                                "startColumn": f.col + 1}}}]}
                for f in result.findings],
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="goltpu-lint",
        description="TPU-invariant static analysis (rules GOL001…GOL010; "
                    "see README 'Static analysis & sanitizers')")
    ap.add_argument("paths", nargs="*",
                    default=["gameoflifewithactors_tpu", "scripts",
                             "tests", "examples"],
                    help="files/directories to lint (default: the package, "
                         "scripts/, tests/ and examples/)")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="grandfathered-findings file (default: "
                         "lint_baseline.json at the repo root when it "
                         "exists; 'none' disables)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings into the baseline "
                         "file and exit 0 (adoption tool — this repo "
                         "keeps the committed baseline empty)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object instead of text")
    ap.add_argument("--strict-baseline", action="store_true",
                    help="stale (unmatched) baseline entries fail the run "
                         "instead of warning")
    ap.add_argument("--sarif", metavar="OUT.json", default=None,
                    help="additionally write findings as SARIF 2.1.0 to "
                         "this path (CI code-scanning artifact)")
    args = ap.parse_args(argv)

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE
    baseline = None
    if (baseline_path and baseline_path != "none"
            # --write-baseline creates the file: a missing target is the
            # expected first-run state, not unusable input
            and not (args.write_baseline
                     and not os.path.exists(baseline_path))):
        try:
            baseline = lint_lib.load_baseline(baseline_path)
        except (OSError, json.JSONDecodeError,
                lint_lib.BaselineError) as exc:
            print(f"goltpu-lint: unusable baseline: {exc}",
                  file=sys.stderr)
            return 2

    # lint from the repo root so finding paths (and thus baseline keys
    # and pragma docs) are repo-relative regardless of the caller's cwd
    paths = []
    for p in args.paths:
        if not os.path.isabs(p) and not os.path.exists(p) \
                and os.path.exists(os.path.join(_REPO, p)):
            p = os.path.relpath(os.path.join(_REPO, p))
        paths.append(p)

    result = lint_lib.lint_paths(paths, baseline=baseline)

    if args.sarif:
        with open(args.sarif, "w") as f:
            json.dump(_sarif_payload(result), f, indent=1)
            f.write("\n")

    if args.write_baseline:
        payload = lint_lib.baseline_payload(
            result.findings + result.baselined)
        out = baseline_path or DEFAULT_BASELINE
        with open(out, "w") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
        print(f"goltpu-lint: wrote {len(payload['findings'])} "
              f"grandfathered finding(s) to {out}")
        return 0 if not result.errors else 2

    stale_fails = args.strict_baseline and result.unused_baseline
    if args.json:
        doc = result.to_dict()
        doc["exit_code"] = (2 if result.errors
                            else 1 if result.findings or stale_fails
                            else 0)
        print(json.dumps(doc, indent=1))
    else:
        for f in result.findings:
            print(f.format())
        for err in result.errors:
            print(f"goltpu-lint: error: {err}", file=sys.stderr)
        for e in result.unused_baseline:
            print(f"goltpu-lint: stale baseline entry (fixed? remove it): "
                  f"{e.get('path')}: {e.get('code')} {e.get('message')}",
                  file=sys.stderr)
        n_files = len([r for r in result.files if r.error is None])
        summary = (f"goltpu-lint: {n_files} file(s), "
                   f"{len(result.findings)} finding(s), "
                   f"{len(result.suppressed)} suppressed by pragma, "
                   f"{len(result.baselined)} baselined")
        print(summary, file=sys.stderr)
    if result.errors:
        return 2
    if result.findings or stale_fails:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
