"""TPU tunnel health probe: classify the axon tunnel's state in <60s.

The tunneled-TPU PJRT plugin on this image has a recurring wedge mode in
which device calls — and in the worst state ``import jax`` itself — hang
forever. A 420s bench watchdog discovering this at end-of-round costs the
round's TPU evidence (VERDICT.md round-1 Weak #2), so bench.py preflights
with this probe and goes straight to its fallback when the tunnel is not
``healthy``.

The probe runs a staged child process and reads how far it got:

    import-start -> import-done -> devices-done -> compute-done

``compute-done`` requires a *scalar readback* of a tiny device op —
``block_until_ready`` is a no-op on the tunnel, so only a dependent
device->host fetch proves the chip actually executed work.

Statuses:
  healthy        TPU present, tiny op + readback completed
  cpu-only       probe completed but no TPU platform was found
  wedged-import  `import jax` hangs (plugin discovery touches the tunnel)
  wedged-init    import ok, device/backend init hangs
  wedged-compute devices enumerate, but the op or its readback hangs
  error          child died with a traceback (e.g. PJRT init failure)

CLI: ``python scripts/tpu_probe.py [--timeout 60] [--json]``; exit code 0
iff healthy. Library: ``probe(timeout) -> dict``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from gameoflifewithactors_tpu.obs import flight as obs_flight  # noqa: E402
from gameoflifewithactors_tpu.obs.registry import REGISTRY  # noqa: E402

_CHILD = r"""
import sys
def stage(s):
    sys.stdout.write("STAGE " + s + "\n"); sys.stdout.flush()
stage("import-start")
import jax
stage("import-done")
stage("init-start")
devices = jax.devices()
plat = devices[0].platform
stage("devices-done %s %d" % (plat, len(devices)))
import jax.numpy as jnp
x = jnp.ones((128, 128), jnp.float32)
val = float(jax.jit(lambda a: (a @ a).sum())(x))  # scalar readback: the only
stage("compute-done %r" % val)                    # real completion proof here
"""


def probe(timeout: float = 60.0, env: dict | None = None) -> dict:
    """Run the staged child; classify how far it got before the deadline.

    ``env`` overrides the child's environment (default: inherit) — tests use
    it to aim the probe at a guaranteed-CPU configuration.
    """
    t0 = time.perf_counter()
    with tempfile.TemporaryFile(mode="w+") as out, tempfile.TemporaryFile(mode="w+") as err:
        p = subprocess.Popen([sys.executable, "-c", _CHILD], stdout=out, stderr=err,
                             env=env)
        try:
            rc = p.wait(timeout=timeout)
            timed_out = False
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
            rc, timed_out = None, True
        out.seek(0)
        err.seek(0)
        stages = [ln[6:].strip() for ln in out.read().splitlines()
                  if ln.startswith("STAGE ")]
        err_tail = err.read()[-2000:]

    result = {
        "status": "error",
        "platform": None,
        "n_devices": 0,
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "stages": stages,
        "detail": "",
    }
    for s in stages:
        if s.startswith("devices-done"):
            _, plat, n = s.split()
            result["platform"] = plat
            result["n_devices"] = int(n)

    last = stages[-1].split()[0] if stages else "(none)"
    if timed_out:
        result["status"] = {
            "(none)": "wedged-import",   # never even reached import-start
            "import-start": "wedged-import",
            "import-done": "wedged-init",
            "init-start": "wedged-init",  # backend/device init hung
            "devices-done": "wedged-compute",
        }.get(last, "wedged-compute")
        result["detail"] = f"child killed after {timeout}s; last stage: {last}"
    elif rc == 0 and last == "compute-done":
        tpu = result["platform"] not in (None, "cpu")
        result["status"] = "healthy" if tpu else "cpu-only"
        result["detail"] = stages[-1]
    else:
        result["detail"] = f"child rc={rc}; last stage: {last}; stderr: {err_tail}"
    # the outcome is fleet evidence, not just a return value: a counter
    # per status for the aggregated /metrics view, and a flight event so
    # a later dump shows when the tunnel wedged relative to the run
    REGISTRY.counter("tpu_probe_total",
                     "tunnel health probes run, by outcome"
                     ).inc(status=result["status"])
    if result["status"].startswith("wedged"):
        REGISTRY.counter("tpu_probe_wedged_total",
                         "probes that found the tunnel wedged, by the "
                         "last stage the child reached"
                         ).inc(stage=last)
    obs_flight.note_event(
        "tpu_probe", {"status": result["status"], "last_stage": last,
                      "platform": result["platform"],
                      "elapsed_s": result["elapsed_s"]})
    return result


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--timeout", type=float,
                    default=float(os.environ.get("TPU_PROBE_TIMEOUT_S", "60")))
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    r = probe(args.timeout)
    if args.json:
        print(json.dumps(r))
    else:
        print(f"{r['status']}  platform={r['platform']} n={r['n_devices']} "
              f"elapsed={r['elapsed_s']}s  {r['detail']}")
    return 0 if r["status"] == "healthy" else 1


if __name__ == "__main__":
    sys.exit(main())
