"""Warm-start evidence: cold-process vs warm-process wall seconds (CPU).

The acceptance artifact for the aot/ subsystem (ISSUE 2): for three
representative specs — the binary packed path (what the pallas backend
falls back to off-TPU), the Generations bit-plane stack, and the
bit-sliced binary LtL path — run the same engine-build + step + sync in
a fresh subprocess twice against one warm-start cache dir. The first
(cold) process pays trace + XLA compile and populates the persistent
compilation cache + AOT registry; the second (warm) process must come in
at <= 50% of the cold wall time, with its compile events attributed
``cache_hit`` / ``aot_loaded`` and ``compile_seconds`` ~ 0.

Writes ``results/warmstart_cpu.json`` (the scoreboard record) and
``results/warmstart_warm_report.json`` (the warm run's full RunReport,
the "compile time disappeared" receipt). Stdlib-only parent, bench.py's
subprocess pattern: safe to run while the TPU tunnel is wedged.

Usage: python scripts/warm_vs_cold.py [--keep-cache DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the measured run: build + first-use stepping of both runner signatures,
# the exact shape of a serving process's first tick
SPECS = [
    {"name": "binary-packed (pallas CPU-fallback path)",
     "spec": {"rule": "B3/S23", "shape": [512, 512], "backend": "packed"}},
    {"name": "generations-planes (brain)",
     "spec": {"rule": "brain", "shape": [512, 512], "backend": "packed"}},
    {"name": "ltl-bit-sliced (R2 box)",
     "spec": {"rule": "R2,C0,M1,S2..6,B3..5,NM", "shape": [512, 512],
              "backend": "packed"}},
]
CHILD_TIMEOUT_S = float(os.environ.get("WARMSTART_CHILD_TIMEOUT_S", "600"))


def _provenance():
    import importlib.util

    path = os.path.join(REPO, "gameoflifewithactors_tpu", "utils",
                        "provenance.py")
    spec = importlib.util.spec_from_file_location("_wvc_provenance", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def child(spec_json: str, report_out: str | None) -> None:
    """One measured process: enable the cache (env-driven), build the
    spec's engine, exercise both runner signatures, serialize the AOT
    runner, report wall + compile attribution as one JSON line."""
    sys.path.insert(0, REPO)
    import axon_guard

    axon_guard.force_cpu(1)

    from gameoflifewithactors_tpu.aot import EngineSpec, serialize_engine
    from gameoflifewithactors_tpu.aot import registry as aot_registry
    from gameoflifewithactors_tpu.obs import COMPILE_LOG

    spec = EngineSpec.from_dict(json.loads(spec_json))
    t0 = time.perf_counter()
    engine = spec.build_engine()
    engine.step(1)
    engine.step(max(2, engine.gens_per_exchange + 1))
    engine.block_until_ready()
    wall = time.perf_counter() - t0
    try:
        serialize_engine(engine)
    except aot_registry.AotUnsupported:
        pass
    events = COMPILE_LOG.events()
    kinds: dict = {}
    for e in events:
        kinds[e.kind] = kinds.get(e.kind, 0) + 1
    if report_out:
        from gameoflifewithactors_tpu.obs.report import build_run_report

        build_run_report(
            engine=engine,
            config={"warm_vs_cold": True, "spec": spec.canonical()},
        ).save(report_out)
    print(json.dumps({
        "wall_seconds": wall,
        "compile_seconds": COMPILE_LOG.total_compile_seconds(),
        "events": kinds,
        "aot_loaded": engine.aot_loaded,
    }))


def run_child(spec: dict, cache_dir: str, report_out: str | None,
              aot: bool = True) -> dict:
    sys.path.insert(0, REPO)
    import axon_guard

    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "GOLTPU_CACHE_DIR": cache_dir,
           "GOLTPU_AOT": "1" if aot else "0",
           "PYTHONPATH": axon_guard.strip_pythonpath()}
    cmd = [sys.executable, os.path.abspath(__file__), "--run-child",
           json.dumps(spec)]
    if report_out:
        cmd += ["--report-out", report_out]
    r = subprocess.run(cmd, capture_output=True, text=True, env=env,
                       timeout=CHILD_TIMEOUT_S)
    if r.returncode != 0:
        sys.stderr.write(r.stderr)
        raise SystemExit(f"child failed (rc={r.returncode})")
    return json.loads(r.stdout.strip().splitlines()[-1])


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--run-child", metavar="SPEC_JSON", default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--report-out", metavar="PATH", default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--keep-cache", metavar="DIR", default=None,
                    help="use (and keep) this cache dir instead of a "
                         "throwaway temp dir")
    args = ap.parse_args()
    if args.run_child:
        child(args.run_child, args.report_out)
        return

    cache_dir = args.keep_cache or tempfile.mkdtemp(prefix="goltpu-wvc-")
    results_dir = os.path.join(REPO, "results")
    os.makedirs(results_dir, exist_ok=True)
    warm_report_path = os.path.join(results_dir, "warmstart_warm_report.json")
    rows = []
    try:
        for item in SPECS:
            name, spec = item["name"], item["spec"]
            sys.stderr.write(f"[cold] {name} ...\n")
            cold = run_child(spec, cache_dir, None)
            sys.stderr.write(
                f"    {cold['wall_seconds']:.2f}s "
                f"({cold['compile_seconds']:.2f}s compiling)\n[warm] "
                f"{name} ...\n")
            warm = run_child(spec, cache_dir, None)
            sys.stderr.write(
                f"    {warm['wall_seconds']:.2f}s "
                f"({warm['compile_seconds']:.2f}s compiling), events "
                f"{warm['events']}, aot_loaded={warm['aot_loaded']}\n")
            rows.append({
                "name": name, "spec": spec,
                "cold_wall_seconds": cold["wall_seconds"],
                "cold_compile_seconds": cold["compile_seconds"],
                "warm_wall_seconds": warm["wall_seconds"],
                "warm_compile_seconds": warm["compile_seconds"],
                "warm_events": warm["events"],
                "warm_aot_loaded": warm["aot_loaded"],
                "warm_over_cold": warm["wall_seconds"] / cold["wall_seconds"],
            })
        # one more warm run with AOT loading off: layer 1 alone — the
        # re-jitted runners must come back as cache_hit events with zero
        # compile seconds; its RunReport is the committed receipt
        sys.stderr.write("[warm, GOLTPU_AOT=0] "
                         f"{SPECS[0]['name']} ...\n")
        layer1 = run_child(SPECS[0]["spec"], cache_dir, warm_report_path,
                           aot=False)
        sys.stderr.write(
            f"    {layer1['wall_seconds']:.2f}s "
            f"({layer1['compile_seconds']:.2f}s compiling), events "
            f"{layer1['events']}\n")
    finally:
        if not args.keep_cache:
            shutil.rmtree(cache_dir, ignore_errors=True)

    total_cold = sum(r["cold_wall_seconds"] for r in rows)
    total_warm = sum(r["warm_wall_seconds"] for r in rows)
    prov = _provenance()
    record = {
        "metric": "warm-start: warm-process / cold-process wall time, "
                  "3 representative specs (cpu)",
        "value": total_warm / total_cold,
        "unit": "warm/cold wall ratio",
        "ok": total_warm <= 0.5 * total_cold,
        "target": "<= 0.5 (ISSUE 2 acceptance)",
        "total_cold_seconds": total_cold,
        "total_warm_seconds": total_warm,
        "specs": rows,
        "layer1_only_warm": {
            "spec": SPECS[0]["spec"],
            "wall_seconds": layer1["wall_seconds"],
            "compile_seconds": layer1["compile_seconds"],
            "events": layer1["events"],
        },
        "warm_report": os.path.relpath(warm_report_path, REPO),
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        **prov.head_stamp(paths=["gameoflifewithactors_tpu/aot",
                                 "gameoflifewithactors_tpu/ops",
                                 "gameoflifewithactors_tpu/engine.py",
                                 "gameoflifewithactors_tpu/obs/compile.py",
                                 "scripts/warm_vs_cold.py"]),
    }
    out = os.path.join(results_dir, "warmstart_cpu.json")
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(json.dumps({k: record[k] for k in
                      ("metric", "value", "unit", "ok")}))
    sys.stderr.write(f"written: {out}\n")


if __name__ == "__main__":
    main()
