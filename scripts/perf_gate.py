"""Perf-regression gate: machine-check a fresh run against a baseline.

The BENCH_r01-r05 trajectory and every RunReport in results/ are JSON on
disk that only a human rereads; this gate makes the comparison a CI
step. It diffs a current artifact against a committed baseline with
per-metric tolerance bands (obs/diff.py) and exits nonzero on
regression.

Both artifact shapes are accepted on either side — a RunReport
(``--telemetry-out``) or a bench record (``bench.py`` stdout /
``BENCH_*.json``, whose driver wrapper shape ``{"parsed": {...}}`` is
unwrapped automatically). Weak-scaling records
(``scripts/weak_scaling.py --out``) ride the bench shape and add the
COST headline ``scaling/single_chip_equivalent_updates_per_sec``; a
record whose single-chip normalizer was stale arrives pre-flagged
``stale`` and gates as skipped. Provenance is honored: a record flagged
``needs_recapture``/``stale`` — or whose commit-stamped measured paths
changed since capture (utils/provenance.py) — gates as **"skipped
(stale)"**, never "ok": a stale anchor proves nothing either way.

Usage:
  python scripts/perf_gate.py BASELINE.json CURRENT.json
  python scripts/perf_gate.py BENCH_r05.json fresh_bench.json --informational
  python scripts/perf_gate.py base_report.json run.json --tolerance 0.4 \\
      --tol phase/=1.0 --tol step/best_cell_updates_per_sec=0.2

When both records carry a sampling-profiler ``profile`` section
(``--profile-sample`` runs, ISSUE 18), a regression verdict adds an
**attribution blame** section ranking op classes by busy-time
contribution delta ("collective_permute +31%, stencil flat") — advisory
output only; the exit-code contract below is unchanged.

Exit codes: 0 = ok or skipped(stale), 1 = regression, 2 = unusable input.
``--informational`` always exits 0 (CI's warm-up mode — report, don't
block) but still prints the real verdict. Stdlib only; loads the differ
and provenance modules standalone (no package import, no jax) so it
works while a TPU tunnel is wedged.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "gameoflifewithactors_tpu")


def _load_module(name: str, path: str):
    """Import one file WITHOUT the package __init__ (which imports jax —
    a hang when the tunnel is wedged; this gate must stay jax-free)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # dataclasses resolves annotations via here
    spec.loader.exec_module(mod)
    return mod


def _load_record(path: str) -> dict:
    with open(path) as f:
        rec = json.load(f)
    if not isinstance(rec, dict):
        raise SystemExit(f"{path}: expected a JSON object, got "
                         f"{type(rec).__name__}")
    # BENCH_rNN.json driver wrappers carry the measurement under "parsed"
    if "metric" not in rec and isinstance(rec.get("parsed"), dict):
        rec = rec["parsed"]
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("baseline", help="committed baseline JSON (RunReport "
                                     "or bench record / BENCH_*.json)")
    ap.add_argument("current", help="fresh artifact to check")
    ap.add_argument("--tolerance", type=float, default=None, metavar="F",
                    help="default relative tolerance band (e.g. 0.3 = "
                         "±30%%); per-metric defaults in obs/diff.py")
    ap.add_argument("--tol", action="append", default=[],
                    metavar="METRIC_PREFIX=F",
                    help="per-metric tolerance override (repeatable), "
                         "e.g. --tol phase/=1.0")
    ap.add_argument("--informational", action="store_true",
                    help="report but never block: exit 0 even on "
                         "regression (CI warm-up mode)")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict + rows as one JSON object")
    args = ap.parse_args(argv)

    diff_lib = _load_module("_gate_diff",
                            os.path.join(_PKG, "obs", "diff.py"))
    try:
        prov = _load_module("_gate_provenance",
                            os.path.join(_PKG, "utils", "provenance.py"))
    except Exception:
        prov = None  # no git / moved tree: PR-2 flags still honored

    overrides = {}
    for item in args.tol:
        if "=" not in item:
            ap.error(f"--tol wants METRIC_PREFIX=F, got {item!r}")
        k, v = item.split("=", 1)
        overrides[k] = float(v)

    try:
        baseline = _load_record(args.baseline)
        current = _load_record(args.current)
    except (OSError, json.JSONDecodeError) as exc:
        print(f"perf gate: unusable input — {exc}", file=sys.stderr)
        return 2

    kw = {"tolerances": overrides, "provenance": prov}
    if args.tolerance is not None:
        kw["default_tolerance"] = args.tolerance
    verdict = diff_lib.gate(baseline, current, **kw)

    status = verdict["status"]
    label = {"ok": "ok", "regression": "REGRESSION",
             "skipped": "skipped (stale)"}.get(status, status)
    if args.json:
        print(json.dumps({
            "perf_gate": True, "status": status, "label": label,
            "informational": args.informational,
            "reason": verdict["reason"],
            "baseline": args.baseline, "current": args.current,
            "rows": [r.to_dict() for r in verdict["rows"]],
            "blame": verdict.get("blame", []),
        }, indent=1))
    else:
        if verdict["rows"]:
            print("\n".join(diff_lib.format_rows(verdict["rows"])))
        # the attribution blame section (ISSUE 18): *why* it regressed,
        # ranked by op-class contribution delta. Advisory — the
        # 0/1/2 exit contract below is unchanged.
        if verdict.get("blame") and status == "regression":
            print("\n".join(diff_lib.format_blame(verdict["blame"])))
        print(f"perf gate: {label} — {verdict['reason']}")
    if status == "regression" and not args.informational:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
