#!/usr/bin/env python
"""Serve smoke: one server process, hundreds of mixed-spec sessions,
one SIGKILL + ``--resume`` cycle, bit-exact oracle checks throughout.

    python scripts/serve_load.py --sessions 200

Launches ``python -m gameoflifewithactors_tpu serve`` on CPU under
``GOLTPU_SANITIZE=1``, creates ``--sessions`` sessions spread over three
spec families and four tenants through the HTTP API, steps them in mixed
rounds, and verifies a sample of grids against the pure-NumPy oracle
(tests/oracle.py — every session's seed is reproducible from its
``rng_seed`` + ``fill``). Then it checkpoints, SIGKILLs the server
mid-flight, relaunches with ``--resume``, and asserts

- every session survived with its generation cursor intact,
- resumed grids are still bit-identical to the oracle,
- sessions keep stepping correctly after the resume,
- ``/metrics`` serves a nonzero ``goltpu_session_steps_total`` for every
  tenant and the ``goltpu_session_queue_depth`` gauge.

Exit 0 = all green. Artifacts (server log, flight dump, checkpoint) land
in ``--out``; the CI job uploads them on failure (tier1.yml serve-smoke).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path
from typing import List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402

# three families (two rules × two shapes, one dead-edge) so compaction
# and placement run per-family, and four tenants for the per-tenant
# counter assertion
FAMILIES = (
    {"rule": "B3/S23", "height": 32, "width": 32, "topology": "torus"},
    {"rule": "B36/S23", "height": 32, "width": 32, "topology": "torus"},
    {"rule": "B3/S23", "height": 16, "width": 32, "topology": "dead"},
)
TENANTS = ("acme", "globex", "initech", "umbrella")


class Server:
    """The serve subprocess + its announced port."""

    def __init__(self, out: Path, env: dict, extra: List[str],
                 resume: bool = False):
        self.out = out
        self.log = open(out / "server.log", "ab")
        cmd = [sys.executable, "-m", "gameoflifewithactors_tpu", "serve",
               "--port", "0",
               "--checkpoint", str(out / "sessions.npz"),
               "--checkpoint-every", "600",
               "--flight-dump", str(out / "serve.flight.jsonl"),
               *extra]
        if resume:
            cmd.append("--resume")
        self.proc = subprocess.Popen(cmd, cwd=_REPO, env=env,
                                     stdout=subprocess.PIPE,
                                     stderr=self.log, text=True)
        self.port: Optional[int] = None

    def read_port(self) -> int:
        line = self.proc.stdout.readline()
        if not line.startswith("SERVE_PORT"):
            raise RuntimeError(
                f"server announced {line!r} instead of SERVE_PORT")
        self.port = int(line.split()[1])
        return self.port

    def request(self, method: str, path: str,
                body: Optional[dict] = None) -> Tuple[int, object]:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}{path}", data=data, method=method)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                raw = resp.read()
                ctype = resp.headers.get("Content-Type", "")
                return resp.status, (json.loads(raw) if
                                     ctype.startswith("application/json")
                                     else raw.decode())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def sigkill(self) -> None:
        os.kill(self.proc.pid, signal.SIGKILL)
        self.proc.wait()

    def close(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self.log.close()


def oracle_grid(spec: dict, fill: float, rng_seed: int,
                gens: int) -> np.ndarray:
    """The exact cells a session must hold after ``gens`` generations —
    same seeding contract as SessionService._seed_words."""
    from gameoflifewithactors_tpu.models.generations import parse_any
    from gameoflifewithactors_tpu.ops.stencil import Topology
    from tests.oracle import numpy_run

    h, w = spec["height"], spec["width"]
    seed = (np.random.default_rng(rng_seed).random((h, w))
            < fill).astype(np.uint8)
    return numpy_run(seed, parse_any(spec["rule"]),
                     Topology(spec["topology"]), gens)


def fetch_grid(server: Server, sid: str) -> Tuple[int, np.ndarray]:
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.serve.service import decode_words

    code, g = server.request("GET", f"/sessions/{sid}/grid")
    if code != 200:
        raise RuntimeError(f"GET grid {sid}: HTTP {code} {g}")
    words = decode_words(g["cells_hex"], g["height"], g["width"] // 32)
    return g["generation"], bitpack.unpack_np(words)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="serve-layer load + kill/resume smoke")
    ap.add_argument("--sessions", type=int, default=200)
    ap.add_argument("--fill", type=float, default=0.35)
    ap.add_argument("--rounds", type=int, default=2,
                    help="mixed step rounds before the kill")
    ap.add_argument("--sample", type=int, default=40,
                    help="sessions whose grids are oracle-checked")
    ap.add_argument("--ladder", default="1,8,64",
                    help="lane ladder passed to the server")
    ap.add_argument("--out", default=None,
                    help="artifact dir (default: ./serve_out)")
    ap.add_argument("--no-kill", action="store_true",
                    help="skip the SIGKILL + resume cycle")
    args = ap.parse_args(argv)

    out = Path(args.out or os.path.join(_REPO, "serve_out"))
    out.mkdir(parents=True, exist_ok=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu", GOLTPU_SANITIZE="1",
               GOLTPU_CACHE_DIR=os.environ.get(
                   "GOLTPU_CACHE_DIR",
                   os.path.join(_REPO, ".goltpu_cache")))
    extra = ["--ladder", args.ladder]
    failures: List[str] = []
    t0 = time.perf_counter()

    server = Server(out, env, extra)
    try:
        server.read_port()
        print(f"serve_load: server up on :{server.port}", flush=True)

        # -- create the fleet -------------------------------------------------
        sessions: List[dict] = []  # {sid, tenant, spec, rng_seed, gens}
        for i in range(args.sessions):
            spec = FAMILIES[i % len(FAMILIES)]
            tenant = TENANTS[i % len(TENANTS)]
            code, info = server.request("POST", "/sessions", {
                "tenant": tenant, "spec": spec, "fill": args.fill,
                "rng_seed": i})
            if code not in (201, 202):
                failures.append(f"create #{i}: HTTP {code} {info}")
                continue
            sessions.append({"sid": info["sid"], "tenant": tenant,
                             "spec": spec, "rng_seed": i, "gens": 0})
        print(f"serve_load: created {len(sessions)} sessions "
              f"({len(FAMILIES)} families, {len(TENANTS)} tenants)",
              flush=True)

        # -- mixed step rounds (divergent cursors on shared lanes) ------------
        for r in range(args.rounds):
            for i, s in enumerate(sessions):
                n = 1 + (i + r) % 4
                code, info = server.request(
                    "POST", f"/sessions/{s['sid']}/step", {"n": n})
                if code != 200:
                    failures.append(f"step {s['sid']}: HTTP {code} {info}")
                    continue
                s["gens"] += n
                if info["generation"] != s["gens"]:
                    failures.append(
                        f"{s['sid']}: generation {info['generation']} != "
                        f"expected {s['gens']}")

        # a stride divisible by len(TENANTS) would sample one tenant only,
        # starving the post-resume per-tenant counter check (the resumed
        # process starts with fresh counters and only sampled sessions
        # step after the kill) — bump it off the tenant period
        stride = max(1, len(sessions) // max(1, args.sample))
        if stride % len(TENANTS) == 0 and len(sessions) > len(TENANTS):
            stride += 1
        sample = sessions[::stride]
        for s in sample:
            gen, got = fetch_grid(server, s["sid"])
            want = oracle_grid(s["spec"], args.fill, s["rng_seed"], s["gens"])
            if gen != s["gens"] or not np.array_equal(got, want):
                failures.append(f"{s['sid']}: pre-kill grid diverged from "
                                f"oracle at gen {gen}")
        print(f"serve_load: {len(sample)} grids oracle-checked pre-kill",
              flush=True)

        # -- SIGKILL + resume -------------------------------------------------
        if not args.no_kill:
            code, ck = server.request("POST", "/admin/checkpoint")
            if code != 200:
                failures.append(f"checkpoint: HTTP {code} {ck}")
            server.sigkill()
            print("serve_load: SIGKILLed the server; resuming", flush=True)
            server.close()
            server = Server(out, env, extra, resume=True)
            server.read_port()
            code, h = server.request("GET", "/healthz")
            live = h.get("sessions", {}).get("live", 0) if code == 200 else 0
            if live != len(sessions):
                failures.append(
                    f"resume lost sessions: {live} live != {len(sessions)}")
            for s in sample:
                gen, got = fetch_grid(server, s["sid"])
                want = oracle_grid(s["spec"], args.fill, s["rng_seed"],
                                   s["gens"])
                if gen != s["gens"] or not np.array_equal(got, want):
                    failures.append(f"{s['sid']}: post-resume grid diverged "
                                    f"(gen {gen}, expected {s['gens']})")
            # stepping must keep working (and stay exact) after the resume
            for s in sample:
                code, info = server.request(
                    "POST", f"/sessions/{s['sid']}/step", {"n": 3})
                if code != 200:
                    failures.append(
                        f"post-resume step {s['sid']}: HTTP {code}")
                    continue
                s["gens"] += 3
                gen, got = fetch_grid(server, s["sid"])
                want = oracle_grid(s["spec"], args.fill, s["rng_seed"],
                                   s["gens"])
                if not np.array_equal(got, want):
                    failures.append(
                        f"{s['sid']}: diverged after post-resume step")
            print(f"serve_load: resume verified on {len(sample)} sessions",
                  flush=True)

        # -- metrics ----------------------------------------------------------
        code, metrics = server.request("GET", "/metrics")
        if code != 200:
            failures.append(f"/metrics: HTTP {code}")
            metrics = ""
        for tenant in TENANTS:
            needle = f'goltpu_session_steps_total{{tenant="{tenant}"}}'
            line = next((ln for ln in metrics.splitlines()
                         if ln.startswith(needle)), None)
            if line is None or float(line.split()[-1]) <= 0:
                failures.append(
                    f"/metrics: no positive steps counter for {tenant}")
        if "goltpu_session_queue_depth" not in metrics:
            failures.append("/metrics: queue depth gauge missing")
    finally:
        server.close()

    wall = time.perf_counter() - t0
    if failures:
        print(f"serve_load: FAILED after {wall:.1f}s "
              f"({len(failures)} failures):", flush=True)
        for f in failures[:20]:
            print(f"  - {f}", flush=True)
        return 1
    print(f"serve_load: OK in {wall:.1f}s — {len(sessions)} sessions, "
          f"{args.rounds} step rounds, "
          f"{'kill/resume verified, ' if not args.no_kill else ''}"
          "all sampled grids bit-identical to oracle", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
