#!/usr/bin/env python
"""fleet_top: live fleet table over N worker ``/metrics`` endpoints.

    python scripts/fleet_top.py w0=127.0.0.1:9001 w1=127.0.0.1:9002
    python scripts/fleet_top.py --once 127.0.0.1:9001 127.0.0.1:9002

Polls every target through a :class:`FleetAggregator` (TTL-cached, so
pointing several fleet_tops at the same fleet does not multiply scrape
load) and renders one row per worker: lanes and slot occupancy,
sessions and distinct tenants, per-proc steps/sec, HBM in use against
the limit, heartbeat misses, retraces (post-warm jit compiles), and the
sampling profiler's duty cycle + measured capture overhead (PROF /
PROF-OH, "-" when unarmed).

Rates and HBM are per-chip numbers: each row reads one process's
gauges, and nothing here sums them across rows (the aggregator refuses
that by construction — ``PerChipSumError``).

``--serve PORT`` additionally exposes the merged exposition at
``http://127.0.0.1:PORT/metrics`` (and ``/fleet`` liveness JSON) for an
external scraper. ``--once`` prints a single table and exits 0 if every
target answered — the CI smoke mode.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from gameoflifewithactors_tpu.obs.aggregate import (  # noqa: E402
    AggregatorServer, FleetAggregator, base_name)

COLUMNS = ("PROC", "UP", "LANES", "SLOTS", "SESS", "TENANTS", "STEPS/S",
           "HBM", "POOL", "HB-MISS", "RETRACE", "STALLS", "PROF",
           "PROF-OH")


def _samples(parsed: Optional[dict], family: str) -> List[tuple]:
    if parsed is None:
        return []
    return [(labels, value) for name, labels, value in parsed["samples"]
            if base_name(name) == family]


def _total(parsed: Optional[dict], family: str) -> float:
    return sum(v for _l, v in _samples(parsed, family))


def _ratio(parsed: Optional[dict], family: str) -> str:
    """A per-chip ratio gauge as a percentage, '-' when unarmed."""
    vals = [v for _l, v in _samples(parsed, family)]
    return f"{max(vals):.1%}" if vals else "-"


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}TiB"


def row_for(proc: str, parsed: Optional[dict]) -> List[str]:
    if parsed is None:
        return [proc, "down"] + ["-"] * (len(COLUMNS) - 2)
    tenants = sorted({labels.get("tenant") for labels, v in
                      _samples(parsed, "sessions_live")
                      if labels.get("tenant") and v > 0})
    slots_live = _total(parsed, "session_lane_slots_live")
    slots_total = _total(parsed, "session_lane_slots_total")
    # per-proc sum over tenants of a same-chip gauge: still one chip's
    # number, so summing here is honest (unlike summing across procs)
    steps = _total(parsed, "tenant_steps_per_sec")
    hbm_use = max((v for _l, v in _samples(parsed, "hbm_bytes_in_use")),
                  default=0.0)
    hbm_lim = max((v for _l, v in _samples(parsed, "hbm_bytes_limit")),
                  default=0.0)
    hbm = (f"{_fmt_bytes(hbm_use)}/{_fmt_bytes(hbm_lim)}"
           if hbm_lim else (_fmt_bytes(hbm_use) if hbm_use else "-"))
    # paged tile pools (memory/pool.py): in-use/capacity summed over
    # this proc's pools — same-chip sums, like the tenant gauges above
    pool_used = _total(parsed, "pool_tiles_in_use")
    pool_free = _total(parsed, "pool_tiles_free")
    pool = (f"{pool_used:.0f}/{pool_used + pool_free:.0f}"
            if (pool_used or pool_free) else "-")
    return [
        proc, "up",
        f"{_total(parsed, 'session_lanes'):.0f}",
        f"{slots_live:.0f}/{slots_total:.0f}",
        f"{_total(parsed, 'sessions_live'):.0f}",
        f"{len(tenants)}",
        f"{steps:.1f}",
        hbm,
        pool,
        f"{_total(parsed, 'elastic_heartbeat_misses_total'):.0f}",
        f"{_total(parsed, 'jit_compiles'):.0f}",
        f"{_total(parsed, 'stalls'):.0f}",
        # sampling-profiler visibility (ISSUE 18): an armed fleet is
        # visibly armed — configured duty cycle and measured capture
        # overhead, both per-chip ratios (max, never summed)
        _ratio(parsed, "profile_duty_cycle"),
        _ratio(parsed, "profile_overhead_ratio"),
    ]


def render_table(view: Dict[str, Optional[dict]]) -> str:
    rows = [list(COLUMNS)] + [row_for(p, parsed)
                              for p, parsed in sorted(view.items())]
    widths = [max(len(r[c]) for r in rows) for c in range(len(COLUMNS))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(r, widths)).rstrip()
        for r in rows)


def parse_targets(raw: List[str]) -> Dict[str, str]:
    targets: Dict[str, str] = {}
    for i, item in enumerate(raw):
        if "=" in item:
            proc, url = item.split("=", 1)
        else:
            proc, url = f"w{i}", item
        targets[proc] = url
    return targets


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="live fleet table over worker /metrics endpoints")
    parser.add_argument("targets", nargs="+",
                        help="worker endpoints, 'name=host:port' or "
                        "'host:port' (named w0, w1, ... in order)")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh period in seconds")
    parser.add_argument("--once", action="store_true",
                        help="print one table and exit (0 iff all up)")
    parser.add_argument("--serve", type=int, default=None, metavar="PORT",
                        help="also serve the merged exposition on "
                        "127.0.0.1:PORT (/metrics, /fleet)")
    args = parser.parse_args(argv)

    agg = FleetAggregator(parse_targets(args.targets),
                          ttl_seconds=min(1.0, args.interval / 2))
    server = None
    if args.serve is not None:
        server = AggregatorServer(agg, port=args.serve).start()
        print(f"fleet_top: aggregate endpoint on "
              f"http://127.0.0.1:{server.port}/metrics", flush=True)
    try:
        while True:
            view = agg.view()
            table = render_table(view)
            if args.once:
                print(table, flush=True)
                return 0 if all(v is not None for v in view.values()) else 1
            sys.stdout.write("\x1b[2J\x1b[H" + table + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        if server is not None:
            server.stop()


if __name__ == "__main__":
    sys.exit(main())
