"""BASELINE.json config #5 at its stated scale: Gosper gun, sparse backend.

Runs a Gosper glider gun centered in a ``--size``² (default 65536²) field on
the activity-tiled sparse engine (ops/sparse.py), and reports gens/sec,
cell-updates/sec (nominal: whole-grid cells × gens / time — the honest
metric for "what a dense step would have had to pay"), active-tile count,
and memory headroom. VERDICT.md round-1 Missing #4: this config had never
been executed at its stated size on any platform.

The 65536² packed grid is 512 MB (+ zero ring); the dense seed would be
4.3 GB, so the gun patch is packed small and placed word-aligned into the
packed field directly — seeding cost stays O(patch), not O(grid).

Prints one JSON line per phase plus a final summary line; ``--out`` also
writes the summary (plus environment metadata) to a JSON file.

Run CPU-only (wedged tunnel) with:
  PYTHONPATH= JAX_PLATFORMS=cpu python scripts/config5_sparse.py --gens 256
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--size", type=int, default=65536)
    ap.add_argument("--gens", type=int, default=512)
    ap.add_argument("--repeats", type=int, default=2)
    ap.add_argument("--capacity", type=int, default=None,
                    help="active-tile capacity (default: sparse engine's)")
    ap.add_argument("--chunk-gens", type=int, default=None, metavar="G",
                    help="temporal chunking depth for the headline run "
                         "(default: the engine's, currently 1)")
    ap.add_argument("--chunk-ab", action="store_true",
                    help="after the headline run, re-run at the rule's max "
                         "chunk depth and report both rates — the on-chip "
                         "A/B that decides whether chunking's scan "
                         "amortization beats its extra window work on TPU "
                         "(on CPU it measured 5x slower)")
    ap.add_argument("--out", default=None, metavar="PATH")
    args = ap.parse_args()

    import jax

    from gameoflifewithactors_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    import jax.numpy as jnp

    from gameoflifewithactors_tpu.models import seeds as seeds_lib
    from gameoflifewithactors_tpu.models.rules import CONWAY
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.ops.sparse import SparseEngineState

    platform = jax.devices()[0].platform
    side = args.size
    if side % bitpack.WORD:
        raise SystemExit(f"--size must be a multiple of {bitpack.WORD}")

    # word-aligned small-patch seeding: O(patch) host work for any grid size
    words = side // bitpack.WORD
    t0 = time.perf_counter()
    grid = seeds_lib.seeded_packed((side, side), "gosper_gun",
                                   top=side // 2, left_word=words // 2)
    opts = {}
    if args.capacity is not None:
        opts["capacity"] = args.capacity
    if args.chunk_gens is not None:
        opts["chunk_gens"] = args.chunk_gens
    state = SparseEngineState(jnp.asarray(grid), CONWAY, **opts)
    del grid
    print(json.dumps({"phase": "seeded", "grid": [side, side],
                      "packed_mb": round(side * words * 4 / 2**20, 1),
                      "seed_s": round(time.perf_counter() - t0, 2),
                      "platform": platform}), flush=True)

    def sync() -> int:
        # block_until_ready is a no-op on the tunnel; a scalar reduction
        # that data-depends on the state is the only real completion proof
        return int(jnp.sum(state.padded.astype(jnp.uint32))) & 0xFFFF

    t0 = time.perf_counter()
    # warm past one full chunk so the bulk chunked program (not just the
    # remainder program) compiles OUTSIDE the timed repetitions
    state.step(max(4, 2 * state.chunk_gens))
    sync()
    print(json.dumps({"phase": "warm", "compile_s": round(time.perf_counter() - t0, 2),
                      "active_tiles": state.active_tiles()}), flush=True)

    best = 0.0
    for _ in range(args.repeats):
        t0 = time.perf_counter()
        state.step(args.gens)
        sync()
        best = max(best, args.gens / (time.perf_counter() - t0))

    gens_done = 4 + args.repeats * args.gens
    pop = bitpack.population(state.packed)
    summary = {
        "metric": f"gens/sec, {side}x{side} Gosper gun (sparse, {platform})",
        "value": best,
        "unit": "gens/sec",
        "nominal_cell_updates_per_sec": best * side * side,
        "active_tiles": state.active_tiles(),
        "total_tiles": (side // state.tile_rows) * (words // state.tile_words),
        "capacity": state.capacity,
        "chunk_gens": state.chunk_gens,
        "population": pop,
        "generations_run": gens_done,
        "grid_bytes": side * words * 4,
        "platform": platform,
    }
    if args.chunk_ab:
        from gameoflifewithactors_tpu.ops.sparse import max_chunk_gens

        g = max_chunk_gens(CONWAY)
        del state  # free the headline run's 512 MB padded buffer first
        cgrid = seeds_lib.seeded_packed(
            (side, side), "gosper_gun", top=side // 2, left_word=words // 2)
        cstate = SparseEngineState(jnp.asarray(cgrid), CONWAY, chunk_gens=g,
                                   **({"capacity": args.capacity}
                                      if args.capacity is not None else {}))
        del cgrid
        cstate.step(2 * g)  # compile + warm
        int(jnp.sum(cstate.padded.astype(jnp.uint32)))
        cbest = 0.0
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            cstate.step(args.gens)
            int(jnp.sum(cstate.padded.astype(jnp.uint32)))
            cbest = max(cbest, args.gens / (time.perf_counter() - t0))
        summary["chunked_gens_per_sec"] = cbest
        summary["chunked_chunk_gens"] = g
        print(json.dumps({"phase": "chunk_ab", "chunk_gens": g,
                          "gens_per_sec": cbest}), flush=True)
    print(json.dumps(summary), flush=True)
    if args.out:
        import platform as platform_mod

        from gameoflifewithactors_tpu.utils import provenance

        record = {
            **summary,
            # provenance stamp (commit + measured_paths) so staleness()
            # can certify or flag this artifact like any persisted record;
            # worklist_item scopes the worklist protocol file to this
            # item's own child function (utils/provenance._protocol_scope)
            **provenance.head_stamp(
                paths=provenance.ITEM_PATHS["config5_sparse"]),
            "worklist_item": "config5_sparse",
            "jax_version": jax.__version__,
            "device": str(jax.devices()[0]),
            "host": platform_mod.node(),
            "python": platform_mod.python_version(),
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)


if __name__ == "__main__":
    sys.exit(main())
