"""Weak-scaling harness: fixed per-device tile, growing device count.

SURVEY.md §8 stage 6 ("weak-scaling harness to v5e-64"). For each device
count n, a (nx, ny) mesh is built (slice-banded when the devices span DCN
slices — parallel/mesh.py), the global grid is sized nx·TH × ny·TW so every
device always steps the same TH×TW tile, and the sharded multi-step runs
the whole generation loop on-device. Efficiency is rate(n) / (n · rate(1)):
1.0 means halo exchange is free, which on ICI it nearly is (two row strips
+ two column strips per tile per generation — see Engine.halo_bytes_per_gen).

Prints one JSON line per device count plus a summary line. On this image
real multi-chip hardware is absent; run under
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
to exercise the plumbing (all "devices" share one host CPU, so measured
efficiency there reflects core contention, not the interconnect — the
number that matters comes from a real slice).

Timing uses the same scalar-readback sync as bench.py: block_until_ready is
a no-op on the tunneled-TPU platform.

COST honesty (Frank McSherry's bar): every point also records
``single_chip_equivalent_updates_per_sec`` — the fleet rate divided by
the device count, in the SAME units as the single-chip BENCH record —
plus ``cost_vs_single_chip``, its ratio against the newest BENCH
single-chip record for this platform. A fleet whose per-chip rate is
far under the single-chip record is scaling overhead, not capability.
The normalizer's provenance rides along: a stale reference (no commit
stamp, or measured paths changed since capture) marks the whole output
record ``stale``/``needs_recapture``, so ``scripts/perf_gate.py``
skips it exactly like a stale BENCH record instead of certifying a
number anchored to a predecessor of HEAD.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)  # allow `python scripts/weak_scaling.py`


def _single_chip_reference(platform: str):
    """The newest BENCH_r*.json single-chip record for this platform —
    the COST normalizer — as {file, metric, value, commit?, ...,
    stale, stale_reason?}; None when no round measured this platform.
    Staleness is re-derived from the record's own commit stamp
    (utils/provenance.py), so a reference whose measured kernel moved
    on — or that never carried a stamp — is named stale here and
    poisons the weak-scaling record the same way (perf_gate skips)."""
    import glob

    from gameoflifewithactors_tpu.utils import provenance

    ref = None
    for path in sorted(glob.glob(os.path.join(_REPO, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = rec.get("parsed") if isinstance(rec, dict) else None
        if not isinstance(parsed, dict):
            continue
        metric = str(parsed.get("metric", ""))
        if (not metric.endswith(f"{platform})")
                or not isinstance(parsed.get("value"), (int, float))):
            continue
        ref = {"file": os.path.basename(path), "metric": metric,
               "value": float(parsed["value"])}
        for k in ("commit", "commit_dirty", "commit_approx",
                  "recorded_at", "measured_paths"):
            if k in parsed:
                ref[k] = parsed[k]
    if ref is not None:
        st = provenance.staleness(ref)
        ref["stale"] = bool(st.get("stale"))
        if ref["stale"]:
            ref["stale_reason"] = st.get("reason", "")
    return ref


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--tile", default=None, metavar="HxW",
                    help="per-device tile in cells (default 4096x4096 TPU, 512x512 CPU)")
    ap.add_argument("--gens", type=int, default=64)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--rule", default="B3/S23")
    ap.add_argument("--counts", default=None,
                    help="comma-separated device counts (default: 1,2,4,... up to all)")
    ap.add_argument("--gens-per-exchange", type=int, default=1, metavar="G",
                    help="G>1 runs the width-G ghost-zone pipeline (one "
                         "halo exchange per G generations, boundary rings "
                         "first so interior compute overlaps the permutes; "
                         "sharded.make_multi_step_packed_ghost), falling "
                         "back to the 1-word deep runner when the tile is "
                         "too small for 2G-deep ghost zones")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="also write {summary, series, provenance stamp} as "
                         "one JSON dict — the scoreboard-visible artifact "
                         "form (bench.py --report)")
    ap.add_argument("--runner", default="packed",
                    choices=["packed", "band", "sparse-tiled"],
                    help="sharded runner under test: 'packed' (per-gen XLA "
                         "SWAR; G>1 switches to the communication-avoiding "
                         "deep runner), 'band' (the slab-kernel row-band "
                         "path auto serves on TPU — interpret mode off-TPU, "
                         "so CPU numbers measure the composition's "
                         "plumbing, not the kernel), 'sparse-tiled' "
                         "(per-tile activity skipping; each device seeded "
                         "one soup blob so per-device activity is constant "
                         "across the sweep)")
    args = ap.parse_args()

    import jax

    from gameoflifewithactors_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    import jax.numpy as jnp

    from gameoflifewithactors_tpu.models.rules import parse_rule
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.ops.stencil import Topology
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
    from gameoflifewithactors_tpu.parallel import sharded

    devices = jax.devices()
    platform = devices[0].platform
    if args.tile:
        th, tw = (int(v) for v in args.tile.split("x"))
    else:
        th, tw = (4096, 4096) if platform != "cpu" else (512, 512)
    if tw % bitpack.WORD:
        raise SystemExit(f"tile width must be a multiple of {bitpack.WORD}")
    rule = parse_rule(args.rule)
    single_ref = _single_chip_reference(platform)

    if args.counts:
        counts = [int(c) for c in args.counts.split(",")]
    else:
        counts, c = [], 1
        while c <= len(devices):
            counts.append(c)
            c *= 2
        if counts[-1] != len(devices):
            counts.append(len(devices))  # non-power-of-two machines
    counts = [c for c in counts if c <= len(devices)]

    def sync(x) -> None:
        x.block_until_ready()
        int(jnp.sum(x.astype(jnp.uint32)))  # dependent fetch: forces completion

    rng = np.random.default_rng(0)
    base = None  # (devices, rate) of the first measured point
    results = []
    for n in counts:
        # shape=None delegates to make_mesh's own selection: slice-banded
        # (factor2d_sliced) when the devices span DCN slices and divide
        # evenly, plain factor2d otherwise — same policy, same guards
        mesh = mesh_lib.make_mesh(None, devices[:n])
        nx, ny = mesh.shape[mesh_lib.ROW_AXIS], mesh.shape[mesh_lib.COL_AXIS]
        H, W = nx * th, ny * tw
        g = args.gens_per_exchange
        exchange = None  # which bulk-exchange runner served G>1, if any
        if args.runner == "sparse-tiled":
            # one soup blob per device tile (1/64 of its area): per-device
            # activity stays constant across the sweep, so the efficiency
            # ratio isolates the runner's collectives (grid + activity-map
            # halos), which is the point of weak scaling
            grid = np.zeros((H, W), dtype=np.uint8)
            bh, bw = max(1, th // 8), max(1, tw // 8)
            for iy in range(nx):
                for ix in range(ny):
                    r0, c0 = iy * th + th // 4, ix * tw + tw // 4
                    grid[r0:r0 + bh, c0:c0 + bw] = rng.integers(
                        0, 2, size=(bh, bw), dtype=np.uint8)
        else:
            grid = rng.integers(0, 2, size=(H, W), dtype=np.uint8)
        packed = jnp.asarray(bitpack.pack_np(grid))
        p = mesh_lib.device_put_sharded_grid(
            packed, mesh, banded=args.runner == "band" and ny > 1)
        if args.runner == "band":
            from gameoflifewithactors_tpu.ops.pallas_stencil import (
                default_interpret,
            )

            gb = g if g > 1 else 8
            if args.gens % gb:
                raise SystemExit(f"--gens must be a multiple of G={gb}")
            band = sharded.make_multi_step_pallas(
                mesh, rule, Topology.TORUS, gens_per_exchange=gb,
                interpret=default_interpret())
            run = lambda s_, n: band(s_, n // gb)
            g = gb
        elif args.runner == "sparse-tiled":
            from gameoflifewithactors_tpu.ops.sparse import auto_tile

            if g > 1:
                # no communication-avoiding variant exists for this
                # runner; silently recording G>1 would label identical
                # runs as different configurations
                raise SystemExit(
                    "--gens-per-exchange applies to the packed and band "
                    "runners, not sparse-tiled")
            tr, twords = auto_tile(th, tw // bitpack.WORD)
            truns = sharded.make_multi_step_packed_sparse_tiled(
                mesh, rule, Topology.TORUS, tile_rows=tr, tile_words=twords)
            act_cell = [sharded.initial_tile_activity(
                packed, mesh, tr, twords)]

            def run(s_, n):
                s_, act_cell[0] = truns(s_, act_cell[0], n)
                return s_
        elif g > 1:
            if mesh_lib.ghost_fits(th, tw // bitpack.WORD, g):
                bulk = sharded.make_multi_step_packed_ghost(
                    mesh, rule, Topology.TORUS, gens_per_exchange=g)
                exchange = "ghost"
            else:
                bulk = sharded.make_multi_step_packed_deep(
                    mesh, rule, Topology.TORUS, gens_per_exchange=g)
                exchange = "deep"
            run = lambda s_, n: bulk(s_, n // g)
            if args.gens % g:
                raise SystemExit(f"--gens must be a multiple of G={g}")
        else:
            run = sharded.make_multi_step_packed(mesh, rule, Topology.TORUS)
        p = run(p, 8 * g)  # compile + warm
        sync(p)
        best = 0.0
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            p = run(p, args.gens)
            sync(p)
            dt = time.perf_counter() - t0
            best = max(best, H * W * args.gens / dt)
        if base is None:
            base = (n, best)
        # efficiency is per-device rate vs the baseline's per-device rate,
        # so a sweep that starts above 1 device still reports 1.0 first
        eff = (best / n) / (base[1] / base[0])
        rec = {
            "devices": n, "mesh": [nx, ny], "grid": [H, W],
            "runner": args.runner,
            "cell_updates_per_sec": best,
            "per_device": best / n,
            # COST honesty: the fleet rate a single chip's share delivers,
            # in the single-chip BENCH record's own units
            "single_chip_equivalent_updates_per_sec": best / n,
            "weak_scaling_efficiency": eff,
            "platform": platform,
        }
        if exchange is not None:
            rec["exchange"] = exchange
        if single_ref is not None and single_ref["value"] > 0:
            rec["cost_vs_single_chip"] = (best / n) / single_ref["value"]
        if args.runner == "sparse-tiled":
            # the rate above counts every grid cell; most are asleep by
            # design, so record the activity too for honest reading
            rec["active_tiles"] = int(jnp.sum(act_cell[0]))
            rec["total_tiles"] = int(act_cell[0].size)
        results.append(rec)
        print(json.dumps(rec), flush=True)

    summary = {
        "metric": f"weak-scaling efficiency, {th}x{tw}/device, {rule.notation} "
                  f"({platform}, runner={args.runner}, "
                  f"G={args.gens_per_exchange})",
        "value": results[-1]["weak_scaling_efficiency"],
        "unit": "fraction",
        "devices": results[-1]["devices"],
        # the COST headline: per-chip rate at the LARGEST device count,
        # same units as (and gated against) the single-chip BENCH record
        "single_chip_equivalent_updates_per_sec":
            results[-1]["single_chip_equivalent_updates_per_sec"],
    }
    if single_ref is not None and single_ref["value"] > 0:
        summary["cost_vs_single_chip"] = (
            summary["single_chip_equivalent_updates_per_sec"]
            / single_ref["value"])
    print(json.dumps(summary))
    if args.out:
        from gameoflifewithactors_tpu.utils import provenance

        paths = [f"gameoflifewithactors_tpu/parallel/{f}" for f in
                 ("sharded.py", "halo.py", "mesh.py")]
        paths += [f"gameoflifewithactors_tpu/ops/{f}" for f in
                  ("packed.py", "sparse.py", "pallas_stencil.py",
                   "_jit.py", "stencil.py", "bitpack.py")]
        paths += ["gameoflifewithactors_tpu/models/rules.py",
                  "scripts/weak_scaling.py"]
        record = {**summary, "series": results,
                  "single_chip_reference": single_ref,
                  **provenance.head_stamp(paths=paths),
                  "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime())}
        if single_ref is None:
            record["stale"] = True
            record["needs_recapture"] = True
            record["stale_reason"] = (
                f"no single-chip BENCH record for platform {platform!r}; "
                "cost_vs_single_chip is unanchored")
        elif single_ref.get("stale"):
            # BENCH semantics: a stale normalizer poisons the record —
            # perf_gate must report "skipped (stale)", never "ok"
            record["stale"] = True
            record["needs_recapture"] = True
            record["stale_reason"] = (
                f"single-chip reference {single_ref['file']} is stale: "
                f"{single_ref.get('stale_reason', '')}")
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    return


if __name__ == "__main__":
    sys.exit(main())
