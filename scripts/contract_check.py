#!/usr/bin/env python3
"""CI gate: prove runner invariants from compiled HLO on the CPU platform.

Enumerates the builder registry (ops/_jit.py BUILDERS), lowers every
registered runner on an 8-virtual-device CPU jax, and checks the
contracts analysis/contracts.py defines: donation really applied, zero
host transfers, collective-permute bytes equal to the closed-form halo
models, and count/byte totals matching the frozen manifest
(results/hlo_contracts.json). Failures name the runner.

Usage:
    python scripts/contract_check.py                 # gate vs the manifest
    python scripts/contract_check.py --strict        # CI: unpinned = fail
    python scripts/contract_check.py --write         # regenerate manifest
    python scripts/contract_check.py --only NAME     # one runner (fast)
    python scripts/contract_check.py --json OUT      # machine-readable

Exit codes (scripts/perf_gate.py contract): 0 = ok or skipped (stale
manifest: pinned under a different jax version — invariants still
enforced), 1 = contract violation, 2 = unusable input (missing manifest
in --strict, unknown --only name).

GOLTPU_CONTRACT_INJECT=<runner> routes that runner through a fault-
injection seam that adds one ppermute to its program — the committed
proof (tests/test_contracts.py) that this gate fails closed.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
import axon_guard  # noqa: E402  (repo-root helper; must not import jax)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="HLO contract gate over the runner-builder registry")
    ap.add_argument("--manifest",
                    default=os.path.join(_REPO, "results",
                                         "hlo_contracts.json"),
                    help="frozen manifest path (default: "
                         "results/hlo_contracts.json)")
    ap.add_argument("--write", action="store_true",
                    help="regenerate the manifest from this run's "
                         "measurements instead of gating against it")
    ap.add_argument("--strict", action="store_true",
                    help="CI mode: a runner the manifest does not pin, "
                         "or a missing manifest, is a failure")
    ap.add_argument("--only", action="append", metavar="NAME",
                    help="check only this runner (repeatable)")
    ap.add_argument("--json", dest="json_out", metavar="OUT",
                    help="also write results as JSON to OUT")
    args = ap.parse_args(argv)

    # CPU staging BEFORE any package import: the package __init__ pulls
    # in jax, and the contract platform must be 8 virtual CPU devices
    axon_guard.force_cpu(8)
    from gameoflifewithactors_tpu.analysis import contracts

    inject = os.environ.get(contracts.ENV_INJECT) or None
    try:
        results = contracts.check_all(only=args.only, inject=inject)
    except KeyError as e:
        print(f"contract-check: {e.args[0]}", file=sys.stderr)
        return 2

    if args.json_out:
        payload = {
            "jax": contracts.jax_version(),
            "results": [dataclasses.asdict(r) for r in results],
        }
        with open(args.json_out, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
            fh.write("\n")

    if args.write:
        manifest = contracts.build_manifest(results)
        contracts.write_manifest(manifest, args.manifest)
        print(f"contract-check: wrote {len(results)} runner contract(s) "
              f"to {args.manifest} (jax {contracts.jax_version()})")
        # still surface invariant violations: a manifest regenerated on
        # top of a broken runner must not launder the breakage into a pin
        bad = [e for r in results for e in r.errors]
        for e in bad:
            print(f"FAIL {e}")
        return 1 if bad else 0

    frozen = contracts.load_manifest(args.manifest)
    if frozen is None and args.strict:
        print(f"contract-check: no manifest at {args.manifest} — "
              "generate one with --write and commit it", file=sys.stderr)
        return 2

    lines = contracts.gate(results, frozen, strict=args.strict,
                           complete=not args.only)
    for line in lines:
        print(line)
    failed = sum(1 for l in lines if l.startswith("FAIL "))
    checked = len(results)
    print(f"contract-check: {checked} runner(s), {failed} failure(s)"
          + (" [strict]" if args.strict else ""))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
