#!/bin/bash
# Healthy-window watcher: probe every 5 min; on a healthy probe, re-capture
# the round's TPU evidence (worklist items + bench configs). Keeps watching
# until EVERY worklist item has a fresh ok:true stamp from after the watcher
# started; each retry runs ONLY the still-stale subset, so a wedge
# mid-capture costs one item's time, not the whole list's, on the next
# healthy window. Safe to re-run; all artifacts merge/persist best-wins.
#
# The probe writes to a FILE, not a pipe: `timeout` kills the probe's
# parent but a tunnel-wedged orphan child keeps a pipe's write end open,
# so `| grep -q` would block far past the timeout (observed: 19 min).
cd /root/repo
WATCH_T0=$(date -u +%Y-%m-%dT%H:%M:%SZ)
export WATCH_T0
# Per-item watchdog floors for the known-slow items (pallas_autotune,
# ltl_bosco) live in tpu_worklist.py's _ITEM_WATCHDOG_S — do NOT export a
# big global WORKLIST_WATCHDOG_S here: it would stretch wedge detection
# on every fast item from 10 to 25 minutes.
# Order matters: pallas_generations and ltl_pallas have NEVER compiled
# natively (VERDICT r3 Missing #1) — a first-ever Mosaic compile is the
# likeliest to need a fix-and-retry loop, so they burn the front of the
# window; then the autotune + trace (VERDICT #2/#3), then recaptures.
ITEMS=pallas_generations,ltl_pallas,ltl_planes,pallas_autotune,profile_trace,pallas_band,bench_packed,ltl_bosco,generations_brain,sparse_tiled,elementary,config5_sparse,pallas_identity,ltl_lowering
export ITEMS
trap 'rm -f "${PROBE_OUT:-}"' EXIT

# A record counts as captured when it is ok AND either (a) recorded this
# watcher run, or (b) provenance-fresh: commit-stamped, clean tree, and the
# measured code paths unchanged since (utils/provenance.py). (b) stops a
# restarted watcher from re-burning TPU windows on evidence that is already
# current; recorded_at alone can't tell that.
stale_items() {  # comma list of worklist items needing capture
  python - <<'EOF'
import importlib.util, json, os
spec = importlib.util.spec_from_file_location(
    "prov", "gameoflifewithactors_tpu/utils/provenance.py")
prov = importlib.util.module_from_spec(spec); spec.loader.exec_module(prov)
t0 = os.environ["WATCH_T0"]
items = os.environ["ITEMS"].split(",")
try:
    d = json.load(open("results/tpu_worklist.json"))
except Exception:
    d = {}
def fresh(k, r):
    if not r or not r.get("ok"):
        return False
    # item= selects the per-item measured path set for records that
    # predate the measured_paths field (utils/provenance.py ITEM_PATHS)
    return r.get("recorded_at", "") >= t0 or not prov.staleness(r, item=k)["stale"]
print(",".join(k for k in items if not fresh(k, d.get(k))))
EOF
}

bench_stale() {  # bench --size values (or "default") needing capture
  python - <<'EOF'
import importlib.util, json, os
spec = importlib.util.spec_from_file_location(
    "prov", "gameoflifewithactors_tpu/utils/provenance.py")
prov = importlib.util.module_from_spec(spec); spec.loader.exec_module(prov)
t0 = os.environ["WATCH_T0"]
try:
    d = json.load(open("results/tpu_best.json"))
except Exception:
    d = {}
for size in ("default", "1024", "8192"):
    r = d.get(f"auto:{size}:B3/S23")
    ok = r and (r.get("recorded_at", "") >= t0 or not prov.staleness(r)["stale"])
    if not ok:
        print(size)
EOF
}

for i in $(seq 1 200); do
  # fresh file per iteration: a SIGTERM-surviving wedged probe from an
  # earlier round still holds an fd and could scribble on a reused file
  rm -f "${PROBE_OUT:-}"
  PROBE_OUT=$(mktemp)
  timeout 90 python scripts/tpu_probe.py > "$PROBE_OUT" 2>/dev/null
  if grep -q '^healthy' "$PROBE_OUT"; then
    STALE=$(stale_items)
    echo "=== healthy at $(date -u +%H:%M:%S), capturing stale: ${STALE:-none} ==="
    if [ -n "$STALE" ]; then
      timeout 4200 python scripts/tpu_worklist.py --force --items "$STALE"
    fi
    # bench configs gated on their own freshness, same as worklist items —
    # a deterministic worklist failure must not re-burn three bench runs
    # (30 min of window) every 5-minute cycle
    for size in $(bench_stale); do
      if [ "$size" = default ]; then
        timeout 600 python bench.py --no-probe
      else
        timeout 600 python bench.py --no-probe --size "$size"
      fi
    done
    if [ -z "$(stale_items)" ] && [ -z "$(bench_stale)" ]; then
      echo "=== capture complete (all items fresh) at $(date -u +%H:%M:%S) ==="
      exit 0
    fi
    echo "=== capture partial at $(date -u +%H:%M:%S); continuing watch ==="
  else
    echo "probe $i: $(head -c 60 "$PROBE_OUT") at $(date -u +%H:%M:%S)"
  fi
  sleep 300
done
echo "gave up after 200 probes"
