#!/bin/bash
# Healthy-window watcher: probe every 5 min; on the first healthy probe,
# re-capture the round's TPU evidence (worklist items + bench configs),
# then exit. Safe to re-run; all artifacts merge/persist best-wins.
cd /root/repo
for i in $(seq 1 60); do
  if timeout 90 python scripts/tpu_probe.py 2>/dev/null | grep -q '^healthy'; then
    echo "=== healthy at $(date -u +%H:%M:%S), capturing ==="
    timeout 3000 python scripts/tpu_worklist.py --force \
      --items pallas_identity,pallas_band,bench_packed,ltl_bosco,generations_brain,config5_sparse
    timeout 600 python bench.py --no-probe
    timeout 600 python bench.py --no-probe --size 1024
    timeout 600 python bench.py --no-probe --size 8192
    echo "=== capture done at $(date -u +%H:%M:%S) ==="
    exit 0
  fi
  echo "probe $i: not healthy at $(date -u +%H:%M:%S)"
  sleep 300
done
echo "gave up after 60 probes"
