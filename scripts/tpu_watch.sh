#!/bin/bash
# Healthy-window watcher: probe every 5 min; on the first healthy probe,
# re-capture the round's TPU evidence (worklist items + bench configs),
# then exit. Safe to re-run; all artifacts merge/persist best-wins.
#
# The probe writes to a FILE, not a pipe: `timeout` kills the probe's
# parent but a tunnel-wedged orphan child keeps a pipe's write end open,
# so `| grep -q` would block far past the timeout (observed: 19 min).
cd /root/repo
trap 'rm -f "${PROBE_OUT:-}"' EXIT
for i in $(seq 1 60); do
  # fresh file per iteration: a SIGTERM-surviving wedged probe from an
  # earlier round still holds an fd and could scribble on a reused file
  rm -f "${PROBE_OUT:-}"
  PROBE_OUT=$(mktemp)
  timeout 90 python scripts/tpu_probe.py > "$PROBE_OUT" 2>/dev/null
  if grep -q '^healthy' "$PROBE_OUT"; then
    echo "=== healthy at $(date -u +%H:%M:%S), capturing ==="
    timeout 3000 python scripts/tpu_worklist.py --force \
      --items pallas_identity,pallas_band,pallas_generations,bench_packed,ltl_bosco,generations_brain,profile_trace,config5_sparse
    timeout 600 python bench.py --no-probe
    timeout 600 python bench.py --no-probe --size 1024
    timeout 600 python bench.py --no-probe --size 8192
    echo "=== capture done at $(date -u +%H:%M:%S) ==="
    exit 0
  fi
  echo "probe $i: $(head -c 60 "$PROBE_OUT") at $(date -u +%H:%M:%S)"
  sleep 300
done
echo "gave up after 60 probes"
