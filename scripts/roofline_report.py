"""Render the measured-roofline section from captured trace evidence.

VERDICT r4 Missing #2 / Next #3: BASELINE.md's roofline is arithmetic until
the ``profile_trace`` worklist item captures a real device trace. This
script turns that capture into the publishable markdown the moment it
lands — measured device busy time, duty cycle, in-kernel rate, and the top
device slices (the DMA-overlap evidence: if the double-buffered copies hide
behind compute, copy slices don't dominate the busy profile) — next to the
arithmetic model's numbers, so the two can be compared line by line.

Usage:
  python scripts/roofline_report.py            # print the section
  python scripts/roofline_report.py --check    # exit 1 if no usable trace

Stdlib only; safe while the tunnel is wedged (it only reads results/).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the arithmetic model's figures for the canonical dispatch (BASELINE.md
# "Roofline sanity bound"): quoted alongside the measurement, never mixed
ARITHMETIC = {
    "packed_2touch_ceiling": 3.3e12,   # 2 HBM touches/gen, 32 cells/word
    "temporal_g8_ceiling": 2.6e13,     # 2 touches per 8 gens
    "hbm_gbps": 820.0,                 # v5e HBM bandwidth
}


def render_roofline(worklist: dict, tpu_best: dict) -> str | None:
    """Markdown section from a captured profile_trace record; None when the
    record is missing/unusable (caller decides how loudly to say so)."""
    rec = worklist.get("profile_trace") or {}
    if not (rec.get("ok") and rec.get("platform") == "tpu"
            and isinstance(rec.get("perfetto"), dict)):
        return None
    p = rec["perfetto"]
    d = rec.get("dispatch", {})
    cells = d.get("cell_updates")
    busy_us = p.get("device_busy_us")
    span_us = p.get("device_span_us")
    if not (cells and busy_us):
        return None
    rate = rec.get("measured_in_kernel_rate", cells / (busy_us / 1e6))
    duty = rec.get("measured_duty_cycle",
                   busy_us / span_us if span_us else None)
    # traffic at g=8 temporal blocking: 2 packed touches per 8 gens
    bytes_moved = cells / 32 * 4 * 2 / 8
    measured_bw = bytes_moved / (busy_us / 1e6) / 1e9
    headline = (tpu_best.get("auto:default:B3/S23") or {}).get("value")

    lines = [
        "## Measured roofline (device trace)",
        "",
        f"Captured by the `profile_trace` worklist item (commit "
        f"{rec.get('commit', '?')}, {rec.get('recorded_at', '?')}): "
        f"{d.get('gens', '?')} generations of the Pallas kernel on a "
        f"{d.get('rows', '?')}x{(d.get('words') or 0) * 32} packed grid, "
        f"one dispatch, perfetto trace in `results/trace/`.",
        "",
        f"- **Measured in-kernel rate**: {rate:.3g} cell-updates/s over "
        f"{busy_us / 1e3:.2f} ms of interval-union device busy time"
        + (f" (canonical bench headline: {headline:.3g}/s — the gap is "
           "dispatch + readback outside the kernel)" if headline else ""),
    ]
    if duty is not None:
        lines.append(
            f"- **Duty cycle**: {duty:.1%} of the {span_us / 1e3:.2f} ms "
            "trace span the device was busy")
    lines += [
        f"- **Implied HBM traffic at g=8 temporal blocking**: "
        f"{measured_bw:.1f} GB/s against the ~{ARITHMETIC['hbm_gbps']:.0f} "
        f"GB/s v5e bound — "
        + ("bandwidth is not the limiter (compute-bound, as the arithmetic "
           "model predicted)" if measured_bw < ARITHMETIC['hbm_gbps'] / 3
           else "approaching the bandwidth bound"),
        f"- **Arithmetic model, for comparison**: 2-touch packed ceiling "
        f"~{ARITHMETIC['packed_2touch_ceiling']:.1e}/s, temporal-blocked "
        f"g=8 traffic ceiling ~{ARITHMETIC['temporal_g8_ceiling']:.1e}/s.",
    ]
    # top slices of the busiest device track (perfetto_summary's "top")
    dev_name = p.get("device_track")
    tops = next((t.get("top") for t in p.get("tracks", [])
                 if t.get("track") == dev_name), None)
    if tops:
        lines += ["", "Top device slices by summed duration (DMA-overlap "
                      "evidence — copy slices dominating here would mean "
                      "Mosaic serialized the double-buffered prefetch):", ""]
        for name, us in list(tops)[:6]:
            lines.append(f"- `{name}` — {us / 1e3:.2f} ms")
    return "\n".join(lines) + "\n"


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--check", action="store_true",
                    help="exit 1 (quietly) when no usable trace exists yet")
    args = ap.parse_args()
    try:
        with open(os.path.join(_REPO, "results", "tpu_worklist.json")) as f:
            worklist = json.load(f)
    except (OSError, json.JSONDecodeError):
        worklist = {}
    try:
        with open(os.path.join(_REPO, "results", "tpu_best.json")) as f:
            tpu_best = json.load(f)
    except (OSError, json.JSONDecodeError):
        tpu_best = {}
    section = render_roofline(worklist, tpu_best)
    if section is None:
        if not args.check:
            print("no usable profile_trace capture in results/tpu_worklist.json"
                  " — the watcher queues it on the next healthy window",
                  file=sys.stderr)
        return 1
    if not args.check:
        print(section, end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
