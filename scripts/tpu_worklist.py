"""Run the round's TPU agenda during a healthy tunnel window.

VERDICT.md round-1 items #2/#3/#5: every TPU-specific claim (north-star
bench, native Pallas, LtL-on-MXU, Generations, sparse at scale) needs
evidence from the real chip. The tunnel wedges intermittently, so this
orchestrator: probes first (scripts/tpu_probe.py), runs each agenda item
in its own watchdog subprocess, and merges results into
``results/tpu_worklist.json`` after *each* item — a wedge mid-list keeps
everything already measured. Safe to re-run; better numbers replace worse.

  python scripts/tpu_worklist.py            # probe, then run all items
  python scripts/tpu_worklist.py --items pallas_identity,bench_packed
  python scripts/tpu_worklist.py --force    # skip the probe gate

Items:
  bench_packed      north-star: bench.py packed @16384² (persists best)
  pallas_identity   native-Mosaic kernel bit-identity vs XLA SWAR on-chip
  pallas_autotune   sweep (block_rows, gens_per_call), record best rate
  ltl_bosco         LtL: on-chip identity vs CPU + dense and bit-sliced rates
  generations_brain Generations path: on-chip bit-identity vs CPU + rate
  ltl_lowering      compiled-HLO evidence the LtL step lowers conv-free (VPU tree)
  ltl_pallas        radius-r LtL kernel: native identity + bosco 16384² rate
  ltl_planes        multi-state (C>=3) LtL plane stack: on-chip identity vs
                    dense + both paths' 8192² rates (auto-routing evidence)
  sparse_tiled      per-tile sharded sparse: native identity + 16384² gun rate
  elementary        1D Wolfram family: numpy-oracle identity + ensemble rate
  config5_sparse    65536² Gosper gun sparse on the chip
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for _p in (_REPO, os.path.dirname(os.path.abspath(__file__))):
    if _p not in sys.path:
        sys.path.insert(0, _p)

OUT_PATH = os.path.join(_REPO, "results", "tpu_worklist.json")
WATCHDOG_S = float(os.environ.get("WORKLIST_WATCHDOG_S", "600"))
# Two items legitimately outrun the default watchdog on the tunnel (the
# autotune sweep's many compiles; ltl_bosco's dense + bit-sliced rate
# pairs) — observed 2026-07-31. Raising the GLOBAL watchdog instead would
# stretch wedge detection on the other 11 items from 10 to 25 minutes
# each, burning most of a healthy window on one wedge-everywhere cycle.
_ITEM_WATCHDOG_S = {"pallas_autotune": 1500.0, "ltl_bosco": 1500.0,
                    # --chunk-ab roughly doubles the run (second 65536²
                    # seed + compile + benchmark); a watchdog kill must
                    # not discard the headline half with it
                    "config5_sparse": 1500.0,
                    # first-ever native Mosaic compiles, several unrolled
                    # kernel variants each (box/diamond/band x topologies)
                    # at minutes per compile (the autotune lesson) — a
                    # watchdog kill mid-compile is also the known
                    # kill-a-child-wedges-the-tunnel hazard, so give the
                    # first compiles room to finish
                    "pallas_generations": 1500.0, "ltl_pallas": 1800.0}


def _watchdog_for(item: str) -> float:
    """Per-item watchdog: the item floor or the (env-raisable) global."""
    return max(WATCHDOG_S, _ITEM_WATCHDOG_S.get(item, 0.0))
# WORKLIST_SMOKE=1 shrinks the rate sections of the newer children so a
# CPU run can validate their logic in seconds (tests use this); the
# identity sections always run full.
_SMOKE = os.environ.get("WORKLIST_SMOKE") == "1"


# ---------------------------------------------------------------------------
# child bodies (run on the real chip; parent enforces the watchdog)
# ---------------------------------------------------------------------------

def _sync_scalar(x):
    """Completion proof on the tunnel: block_until_ready is a no-op there,
    only a data-dependent scalar readback shows the chip really finished."""
    import jax.numpy as jnp

    return int(jnp.sum(x.astype(jnp.uint32))) & 0xFFFF


def _device_equal(a, b) -> bool:
    """Compare ON device — full-array fetches can fail on the tunnel where
    scalar-reduction fetches succeed."""
    import jax.numpy as jnp

    return bool(jnp.array_equal(a, b))


def child_bench_packed() -> dict:
    # --backend packed explicitly: the default (auto) resolves to pallas on
    # TPU, which silently replaced the only packed-SWAR evidence in round 3
    # (ADVICE r3). The pallas number already lives in tpu_best.json's
    # auto:default record; this item owns the packed path.
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"), "--no-probe",
         "--backend", "packed"],
        capture_output=True, text=True, timeout=WATCHDOG_S)
    line = next((ln for ln in reversed(r.stdout.strip().splitlines())
                 if ln.startswith("{")), None)
    if r.returncode or line is None:
        return {"ok": False, "detail": (r.stderr or r.stdout)[-800:]}
    result = json.loads(line)
    if result.get("persisted"):
        # bench fell back to its persisted store: NOT a fresh measurement —
        # marking it ok would let the watcher count an un-re-measured item
        # as captured and exit without real TPU evidence
        return {**result, "ok": False,  # ok LAST: result carries ok:true
                "detail": "bench served a persisted record; no fresh TPU measurement"}
    if "cpu" in result.get("metric", ""):
        # bench's CPU fallback: a host number must not stand in for the
        # packed TPU north-star (the watcher would count it captured)
        return {**result, "ok": False,
                "detail": "bench fell back to CPU; no TPU measurement"}
    return {"ok": True, **result}


def child_pallas_identity() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models.rules import CONWAY
    from gameoflifewithactors_tpu.ops.packed import multi_step_packed
    from gameoflifewithactors_tpu.ops.pallas_stencil import multi_step_pallas, supported
    from gameoflifewithactors_tpu.ops.stencil import Topology

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(7)
    out = {"platform": platform, "cases": []}
    for (h, w) in ((512, 4096), (1024, 8192)):
        grid = rng.integers(0, 2 ** 32, size=(h, w // 32), dtype=np.uint32)
        p = jnp.asarray(grid)
        assert supported(p.shape, on_tpu=True)
        for topology in (Topology.TORUS, Topology.DEAD):
            for gens in (1, 8, 23):
                want = multi_step_packed(p, gens, rule=CONWAY, topology=topology)
                got = multi_step_pallas(p, gens, rule=CONWAY, topology=topology,
                                        interpret=False)
                same = _device_equal(got, want)
                out["cases"].append({"shape": [h, w], "topology": topology.value,
                                     "gens": gens, "bit_identical": same})
                if not same:
                    out["ok"] = False
                    return out
    out["ok"] = True
    return out


def child_pallas_autotune() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models.rules import CONWAY
    from gameoflifewithactors_tpu.ops.pallas_stencil import multi_step_pallas
    from gameoflifewithactors_tpu.ops.stencil import Topology

    side = 16384
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.integers(0, 2 ** 32, size=(side, side // 32), dtype=np.uint32))
    results, best = [], None
    # bh and g must be multiples of 8 natively (sublane-aligned DMA offsets).
    # g > 16 is excluded: the in-kernel generation loop is unrolled g times
    # and Mosaic compile time on those kernels (minutes each) blew two item
    # watchdogs, while the HBM-traffic win beyond g=16 is marginal — the
    # kernel is already compute-bound there (see results/tpu_worklist.json).
    for bh in (256, 512, 1024):
        for g in (8, 16):
            if g > bh:
                continue
            try:
                # long runs (>= 1024 gens) wash out the ~65 ms/dispatch
                # tunnel latency that swamped short measurements; chaining
                # with donate=True mirrors how Engine drives the kernel
                run = lambda s, n: multi_step_pallas(
                    s, n, rule=CONWAY, topology=Topology.TORUS,
                    block_rows=bh, gens_per_call=g, interpret=False,
                    donate=True)
                q = run(jnp.array(p), 2 * g)   # compile + warm
                _sync_scalar(q)
                gens = max(1024, 8 * g)
                rate = 0.0
                for _ in range(2):
                    t0 = time.perf_counter()
                    q = run(q, gens)
                    _sync_scalar(q)
                    rate = max(rate, side * side * gens / (time.perf_counter() - t0))
                rec = {"block_rows": bh, "gens_per_call": g, "rate": rate}
                results.append(rec)
                if best is None or rate > best["rate"]:
                    best = rec
            except Exception as e:  # Mosaic may reject some configs
                results.append({"block_rows": bh, "gens_per_call": g,
                                "error": str(e)[:300]})
    return {"ok": best is not None, "best": best, "sweep": results,
            "platform": jax.devices()[0].platform}


def _bench_rate(step, state, side: int, gens: int):
    """Shared measurement protocol: warm 4 gens, then best of 2 timed reps
    of ``gens`` generations (>= 512 so the ~65 ms/dispatch tunnel latency
    doesn't dominate), each closed by a scalar readback."""
    state = step(state, 4)
    _sync_scalar(state)
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        state = step(state, gens)
        _sync_scalar(state)
        best = max(best, side * side * gens / (time.perf_counter() - t0))
    return best


def _rule_child(rule_name: str, side: int) -> dict:
    """On-chip bit-identity vs the CPU backend + measured rate (dense path)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models.generations import parse_any
    from gameoflifewithactors_tpu.models.ltl import LtLRule
    from gameoflifewithactors_tpu.ops.generations import multi_step_generations
    from gameoflifewithactors_tpu.ops.ltl import multi_step_ltl
    from gameoflifewithactors_tpu.ops.stencil import Topology

    rule = parse_any(rule_name)
    n_states = getattr(rule, "states", 2)
    run = (multi_step_ltl if isinstance(rule, LtLRule) else multi_step_generations)
    rng = np.random.default_rng(3)
    dev = jax.devices()[0]
    cpu = jax.devices("cpu")[0]

    # bit-identity on a small grid: same program, chip vs host CPU backend
    small = rng.integers(0, n_states, size=(256, 256), dtype=np.uint8)
    with jax.default_device(cpu):
        want = run(jnp.asarray(small), 16, rule=rule, topology=Topology.TORUS)
    got = run(jax.device_put(jnp.asarray(small), dev), 16, rule=rule,
              topology=Topology.TORUS)
    identical = _device_equal(got, jax.device_put(want, dev))

    big = jnp.asarray(rng.integers(0, n_states, size=(side, side), dtype=np.uint8))
    gens = 512
    best = _bench_rate(
        lambda st, n: run(st, n, rule=rule, topology=Topology.TORUS), big,
        side, gens)
    out = {"ok": identical, "bit_identical_vs_cpu": identical,
           "rule": rule.notation, "side": side,
           "cell_updates_per_sec": best, "platform": dev.platform}

    if isinstance(rule, LtLRule):
        # bit-sliced packed path: on-chip identity vs dense + its own rate
        # (auto routes LtL to packed on TPU only if this wins — evidence!)
        from gameoflifewithactors_tpu.ops import bitpack
        from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_packed

        small_j = jnp.asarray(small)
        got_pk = bitpack.unpack(multi_step_ltl_packed(
            bitpack.pack(small_j), 16, rule=rule, topology=Topology.TORUS))
        out["packed_bit_identical"] = _device_equal(got_pk.astype(jnp.uint8), got)
        out["ok"] = out["ok"] and out["packed_bit_identical"]
        out["packed_cell_updates_per_sec"] = _bench_rate(
            lambda st, n: multi_step_ltl_packed(
                st, n, rule=rule, topology=Topology.TORUS, donate=True),
            bitpack.pack(big), side, gens)
    else:
        # bit-plane packed path: on-chip identity vs dense + its own rate
        from gameoflifewithactors_tpu.ops.packed_generations import (
            multi_step_packed_generations,
            pack_generations_for,
            unpack_generations,
        )

        small_j = jnp.asarray(small)
        # `got` above is the same 16-gen dense program on the same device
        got_p = unpack_generations(multi_step_packed_generations(
            pack_generations_for(small_j, rule), 16, rule=rule,
            topology=Topology.TORUS))
        out["planes_bit_identical"] = _device_equal(got_p, got)
        out["ok"] = out["ok"] and out["planes_bit_identical"]
        out["planes_cell_updates_per_sec"] = _bench_rate(
            lambda st, n: multi_step_packed_generations(
                st, n, rule=rule, topology=Topology.TORUS, donate=True),
            pack_generations_for(big, rule), side, gens)
    return out


def child_ltl_bosco() -> dict:
    return _rule_child("bosco", 4096)


def child_generations_brain() -> dict:
    return _rule_child("brain", 4096)


def child_ltl_lowering() -> dict:
    """Static evidence the LtL step lowers well on TPU.

    History: the first LtL design routed the radius-r box count through
    lax.conv "for the MXU"; measured on chip it ran at 1.2e8 cell-updates/s
    (~50x below the byte-stencil path) because XLA's TPU conv lowering
    mangles degenerate 1-channel shapes. ops/ltl.py now uses a log-tree of
    shifted integer adds. The check: the compiled step contains NO
    convolution (the bad lowering is gone) and only a handful of fusions
    (the slice/add tree fused into a few VPU passes)."""
    import re

    import jax
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models.ltl import parse_ltl
    from gameoflifewithactors_tpu.ops.ltl import step_ltl
    from gameoflifewithactors_tpu.ops.stencil import Topology

    rule = parse_ltl("bosco")
    g = jnp.asarray(np.zeros((512, 512), dtype=np.uint8))
    # goltpu: ignore[GOL006] -- introspection-only lower/compile: the HLO text is the product, nothing is dispatched
    txt = (jax.jit(lambda x: step_ltl(x, rule=rule, topology=Topology.TORUS))
           .lower(g).compile().as_text())
    convs = re.findall(r"= *\S+ (?:convolution|conv)\b[^\n]*", txt)
    fusions = re.findall(r"= *\S+ fusion\(", txt)
    return {"ok": not convs, "n_convolutions": len(convs),
            "n_fusions": len(fusions),
            "platform": jax.devices()[0].platform}


def child_pallas_band() -> dict:
    """Sharded row-band runner (parallel/sharded.py make_multi_step_pallas)
    on a (1, 1) mesh over the real chip: proves the *slab* variant of the
    Mosaic kernel (zero-filled out-of-range halos, no per-gen re-zero)
    compiles natively and is bit-identical to the XLA SWAR path, and that
    the band composition preserves the kernel's single-chip rate."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models.rules import CONWAY
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.ops.packed import multi_step_packed
    from gameoflifewithactors_tpu.ops.stencil import Topology
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
    from gameoflifewithactors_tpu.parallel import sharded

    m = mesh_lib.make_mesh((1, 1), jax.devices()[:1])
    rng = np.random.default_rng(11)
    out = {"platform": jax.devices()[0].platform, "cases": []}
    # both topologies: DEAD proves the SMEM edge-code exterior re-zero
    # (dead_band kernel variant) compiles and is exact natively
    for (h, w), g, chunks, topo in (
            ((1024, 4096), 8, 2, Topology.TORUS),
            ((512, 8192), 16, 3, Topology.TORUS),
            ((1024, 4096), 8, 2, Topology.DEAD)):
        grid = rng.integers(0, 2, size=(h, w), dtype=np.uint8)
        p = bitpack.pack(jnp.asarray(grid))
        want = multi_step_packed(p, g * chunks, rule=CONWAY, topology=topo)
        run = sharded.make_multi_step_pallas(
            m, CONWAY, topology=topo, gens_per_exchange=g, interpret=False)
        got = run(mesh_lib.device_put_sharded_grid(p, m), chunks)
        same = _device_equal(got, want)
        out["cases"].append({"shape": [h, w], "g": g, "chunks": chunks,
                             "topology": topo.value, "bit_identical": same})
        if not same:
            out["ok"] = False
            return out

    # rate on the bench shape, same long-run protocol as _bench_rate
    side = 16384
    p = mesh_lib.device_put_sharded_grid(jnp.asarray(
        rng.integers(0, 2 ** 32, size=(side, side // 32), dtype=np.uint32)), m)
    run = sharded.make_multi_step_pallas(
        m, CONWAY, gens_per_exchange=8, donate=True, interpret=False)
    p = run(p, 2)
    _sync_scalar(p)
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        p = run(p, 128)  # 1024 generations
        _sync_scalar(p)
        best = max(best, side * side * 1024 / (time.perf_counter() - t0))
    out["ok"] = True
    out["band_cell_updates_per_sec"] = best
    return out


def child_pallas_generations() -> dict:
    """Native Mosaic validation + rate for the Generations bit-plane
    kernel (ops/pallas_stencil.py multi_step_pallas_generations): on-chip
    bit-identity vs the XLA bit-plane path, then the bench-shape rate vs
    the XLA path under the same long-run protocol."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models.generations import parse_any
    from gameoflifewithactors_tpu.ops.packed_generations import (
        multi_step_packed_generations,
        pack_generations_for,
    )
    from gameoflifewithactors_tpu.ops.pallas_stencil import (
        default_interpret,
        multi_step_pallas_generations,
    )
    from gameoflifewithactors_tpu.ops.stencil import Topology

    rule = parse_any("brain")
    rng = np.random.default_rng(5)
    # native Mosaic on the chip; the WORKLIST_SMOKE CPU validation runs
    # the same logic in interpret mode at shrunk shapes (as ltl_pallas)
    interpret = default_interpret() if _SMOKE else False
    out = {"platform": jax.devices()[0].platform, "rule": rule.notation,
           "cases": []}
    ih, iw = (128, 512) if _SMOKE else (512, 4096)
    small = pack_generations_for(jnp.asarray(
        rng.integers(0, rule.states, size=(ih, iw), dtype=np.uint8)), rule)
    for topology in (Topology.TORUS, Topology.DEAD):
        for gens in ((8,) if _SMOKE else (8, 23)):
            want = multi_step_packed_generations(small, gens, rule=rule,
                                                 topology=topology)
            got = multi_step_pallas_generations(
                jnp.array(small), gens, rule=rule, topology=topology,
                interpret=interpret)
            same = _device_equal(got, want)
            out["cases"].append({"topology": topology.value, "gens": gens,
                                 "bit_identical": same})
            if not same:
                out["ok"] = False
                return out

    side, gens = (1024, 16) if _SMOKE else (16384, 1024)
    big = pack_generations_for(jnp.asarray(
        rng.integers(0, rule.states, size=(side, side), dtype=np.uint8)), rule)
    runs = {
        "pallas": lambda s, n: multi_step_pallas_generations(
            s, int(n), rule=rule, topology=Topology.TORUS,
            interpret=interpret, donate=True),
        "xla_planes": lambda s, n: multi_step_packed_generations(
            s, n, rule=rule, topology=Topology.TORUS, donate=True),
    }
    for name, run in runs.items():
        out[f"{name}_cell_updates_per_sec"] = _bench_rate(
            run, jnp.array(big), side, gens)
    out["ok"] = True
    return out


def child_profile_trace() -> dict:
    """A real profiler trace of the Pallas kernel (utils/profiling.py),
    captured as a perfetto trace into ``results/trace/`` and summarized
    into measured numbers (VERDICT round-2 item #6: replace the
    arithmetic roofline with a measured one): interval-union busy time
    per device track gives the kernel's measured duty cycle and the
    measured in-kernel cell-update rate for the 64-generation dispatch."""
    import glob
    import os
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models.rules import CONWAY
    from gameoflifewithactors_tpu.ops.pallas_stencil import (
        default_interpret,
        multi_step_pallas,
    )
    from gameoflifewithactors_tpu.ops.stencil import Topology
    from gameoflifewithactors_tpu.utils.profiling import perfetto_summary

    interp = default_interpret()  # native on TPU; CPU smoke uses interpret
    rng = np.random.default_rng(2)
    rows, words, gens = (256, 8, 8) if _SMOKE else (4096, 512, 64)
    p = jnp.asarray(rng.integers(0, 2 ** 32, size=(rows, words), dtype=np.uint32))
    p = multi_step_pallas(p, 8, rule=CONWAY, topology=Topology.TORUS,
                          interpret=interp)  # warm
    _sync_scalar(p)
    final_dir = os.path.join(_REPO, "results", "trace")
    if _SMOKE:
        # validation run: must not clobber a real captured chip trace
        out_dir = tempfile.mkdtemp(prefix="trace_smoke_")
    else:
        # capture into a sibling dir and swap AFTER the capture succeeds:
        # a wedge mid-capture (watchdog kill) must not have already
        # deleted the previous window's good trace
        out_dir = final_dir + ".new"
        shutil.rmtree(out_dir, ignore_errors=True)
    os.makedirs(out_dir, exist_ok=True)
    jax.profiler.start_trace(out_dir, create_perfetto_trace=True)
    try:
        p = multi_step_pallas(p, gens, rule=CONWAY, topology=Topology.TORUS,
                              interpret=interp)
        _sync_scalar(p)
    finally:
        jax.profiler.stop_trace()
    if not _SMOKE:
        if any(os.path.isfile(f) for f in
               glob.glob(os.path.join(out_dir, "**", "*"), recursive=True)):
            # move the old dir ASIDE (atomic) rather than rmtree-ing it in
            # place: a partial rmtree under ignore_errors would make the
            # following replace raise AFTER a successful capture (ADVICE r3).
            # The aside name is unique per run — a fixed ".old" could be
            # left non-empty by a killed predecessor and collide here.
            old_dir = tempfile.mkdtemp(prefix="trace_old_",
                                       dir=os.path.dirname(final_dir))
            if os.path.isdir(final_dir):
                os.replace(final_dir, os.path.join(old_dir, "trace"))
            os.replace(out_dir, final_dir)
            shutil.rmtree(old_dir, ignore_errors=True)
            out_dir = final_dir
        else:
            # empty capture: don't leave a stale trace.new behind (the
            # globs below return [] for the removed dir -> ok: False)
            shutil.rmtree(out_dir, ignore_errors=True)
    files = [f for f in glob.glob(os.path.join(out_dir, "**", "*"),
                                  recursive=True) if os.path.isfile(f)]
    sizes = {os.path.relpath(f, out_dir): os.path.getsize(f) for f in files}
    perfetto = [f for f in files if f.endswith("perfetto_trace.json.gz")]
    result: dict = {
        "ok": sum(sizes.values()) > 0,
        "trace_bytes": sum(sizes.values()),
        "n_files": len(sizes),
        "largest": sorted(sizes.items(), key=lambda kv: -kv[1])[:3],
        "platform": jax.devices()[0].platform,
        "dispatch": {"rows": rows, "words": words, "gens": gens,
                     "cell_updates": rows * words * 32 * gens},
    }
    if perfetto:
        summ = perfetto_summary(perfetto[0])
        result["perfetto"] = summ
        busy_s = summ["device_busy_us"] / 1e6
        if summ["device_tracks"] and busy_s > 0:
            # measured, not arithmetic: cell-updates over the busiest
            # device track's interval-union busy seconds
            result["measured_in_kernel_rate"] = (
                rows * words * 32 * gens / busy_s)
            result["measured_duty_cycle"] = (
                summ["device_busy_us"] / summ["device_span_us"]
                if summ["device_span_us"] else None)
    if _SMOKE:
        shutil.rmtree(out_dir, ignore_errors=True)
    return result


def child_ltl_pallas() -> dict:
    """The radius-r LtL temporal-blocked kernel natively: on-chip
    bit-identity vs the XLA bit-sliced path, then the bench-shape rate
    for bosco (r=5) vs that path under the long-run protocol."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models.generations import parse_any
    from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_packed
    from gameoflifewithactors_tpu.ops.pallas_stencil import (
        default_interpret,
        ltl_supported,
        multi_step_ltl_pallas,
    )
    from gameoflifewithactors_tpu.ops.stencil import Topology

    rule = parse_any("bosco")
    rng = np.random.default_rng(17)
    # native Mosaic on the chip; the WORKLIST_SMOKE CPU validation runs
    # the same logic in interpret mode (smaller shapes below)
    interpret = default_interpret() if _SMOKE else False
    out = {"platform": jax.devices()[0].platform, "cases": []}
    for (h, w) in (((128, 512),) if _SMOKE else ((512, 4096), (1024, 8192))):
        p = jnp.asarray(rng.integers(0, 2 ** 32, size=(h, w // 32),
                                     dtype=np.uint32))
        assert ltl_supported(p.shape, rule, on_tpu=not interpret)
        for topology in (Topology.TORUS, Topology.DEAD):
            for gens in ((8,) if _SMOKE else (8, 19)):
                want = multi_step_ltl_packed(p, gens, rule=rule,
                                             topology=topology)
                got = multi_step_ltl_pallas(p, gens, rule=rule,
                                            topology=topology,
                                            interpret=interpret)
                same = _device_equal(got, want)
                out["cases"].append({"shape": [h, w],
                                     "topology": topology.value,
                                     "gens": gens, "bit_identical": same})
                if not same:
                    out["ok"] = False
                    return out

    # diamond (von Neumann) neighborhood: the per-row-separable sum must
    # compile natively and stay exact too
    diamond = parse_any("R2,C0,M0,S6..11,B6..9,NN")
    dh, dw = (128, 512) if _SMOKE else (512, 4096)
    dgens = 8 if _SMOKE else 16
    p = jnp.asarray(rng.integers(0, 2 ** 32, size=(dh, dw // 32),
                                 dtype=np.uint32))
    for topology in (Topology.TORUS, Topology.DEAD):
        want = multi_step_ltl_packed(p, dgens, rule=diamond,
                                     topology=topology)
        got = multi_step_ltl_pallas(p, dgens, rule=diamond,
                                    topology=topology, interpret=interpret)
        same = _device_equal(got, want)
        out["cases"].append({"neighborhood": "N", "topology": topology.value,
                             "bit_identical": same})
        if not same:
            out["ok"] = False
            return out

    # band-runner composition on a (1, 1) mesh: the slab-mode LtL kernel
    # (+ DEAD edge code) must compile natively and stay exact
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
    from gameoflifewithactors_tpu.parallel import sharded

    m = mesh_lib.make_mesh((1, 1), jax.devices()[:1])
    bh_, bw_ = (128, 512) if _SMOKE else (512, 4096)
    bchunks = 1 if _SMOKE else 2
    p = jnp.asarray(rng.integers(0, 2 ** 32, size=(bh_, bw_ // 32),
                                 dtype=np.uint32))
    for topology in (Topology.TORUS, Topology.DEAD):
        want = multi_step_ltl_packed(p, 8 * bchunks, rule=rule,
                                     topology=topology)
        run = sharded.make_multi_step_ltl_pallas(
            m, rule, topology, gens_per_exchange=8, interpret=interpret)
        got = run(mesh_lib.device_put_sharded_grid(p, m), bchunks)
        same = _device_equal(got, want)
        out["cases"].append({"band": True, "topology": topology.value,
                             "bit_identical": same})
        if not same:
            out["ok"] = False
            return out

    # rate at the bench shape, both paths, long-run protocol
    side, gens = (1024, 16) if _SMOKE else (16384, 256)
    big = rng.integers(0, 2 ** 32, size=(side, side // 32), dtype=np.uint32)
    rates = {}
    for name, runner in (
            ("pallas", lambda s, n: multi_step_ltl_pallas(
                s, int(n), rule=rule, topology=Topology.TORUS,
                interpret=interpret, donate=True)),
            ("packed", lambda s, n: multi_step_ltl_packed(
                s, n, rule=rule, topology=Topology.TORUS, donate=True))):
        # fresh buffer per runner: donate=True consumes it
        s = runner(jnp.asarray(big), 8)
        _sync_scalar(s)
        best = 0.0
        for _ in range(2):
            t0 = time.perf_counter()
            s = runner(s, gens)
            _sync_scalar(s)
            best = max(best, side * side * gens / (time.perf_counter() - t0))
        rates[name] = best
    out["ok"] = True
    out["cell_updates_per_sec"] = rates
    return out


def child_ltl_planes() -> dict:
    """Multi-state (C >= 3) LtL on the bit-plane stack, on chip: identity
    vs the dense byte path (the oracle-pinned reference,
    ops/ltl.py step_ltl_ext multistate branch), then the bench-shape rate
    for BOTH paths — the evidence that decides whether engine auto should
    route C >= 3 LtL to planes on TPU (today it stays dense, routed on
    this measurement's absence)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models.generations import parse_any
    from gameoflifewithactors_tpu.ops.ltl import multi_step_ltl
    from gameoflifewithactors_tpu.ops.packed_generations import (
        pack_generations_for,
        unpack_generations,
    )
    from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_planes
    from gameoflifewithactors_tpu.ops.stencil import Topology

    rule = parse_any("R2,C4,M1,S3..8,B5..9")
    rng = np.random.default_rng(23)
    out = {"platform": jax.devices()[0].platform, "rule": rule.notation,
           "cases": []}
    ih, iw, igens = (128, 256, 8) if _SMOKE else (512, 1024, 16)
    small = rng.integers(0, rule.states, size=(ih, iw), dtype=np.uint8)
    for topology in (Topology.TORUS, Topology.DEAD):
        want = multi_step_ltl(jnp.asarray(small), igens, rule=rule,
                              topology=topology)
        got = unpack_generations(multi_step_ltl_planes(
            pack_generations_for(jnp.asarray(small), rule), igens,
            rule=rule, topology=topology))
        same = _device_equal(got, want)
        out["cases"].append({"topology": topology.value, "gens": igens,
                             "bit_identical": same})
        if not same:
            out["ok"] = False
            return out

    side, gens = (1024, 16) if _SMOKE else (8192, 256)
    big = rng.integers(0, rule.states, size=(side, side), dtype=np.uint8)
    rates = {}
    for name, prep, runner in (
            ("planes",
             lambda g: pack_generations_for(jnp.asarray(g), rule),
             lambda s, n: multi_step_ltl_planes(
                 s, n, rule=rule, topology=Topology.TORUS, donate=True)),
            ("dense",
             jnp.asarray,
             lambda s, n: multi_step_ltl(
                 s, n, rule=rule, topology=Topology.TORUS, donate=True))):
        rates[name] = _bench_rate(runner, prep(big), side, gens)
    out["ok"] = True
    out["cell_updates_per_sec"] = rates
    return out


def child_sparse_tiled() -> dict:
    """Per-tile sharded sparse (parallel/sharded.py
    make_multi_step_packed_sparse_tiled, round-3 feature) on a (1, 1) mesh
    over the real chip: native bit-identity vs the XLA packed path on a
    gun universe, then the config-#5-shaped rate at 16384² (gens/s with
    the activity map staying sparse)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models import seeds
    from gameoflifewithactors_tpu.models.rules import CONWAY
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.ops.packed import multi_step_packed
    from gameoflifewithactors_tpu.ops.sparse import auto_tile
    from gameoflifewithactors_tpu.ops.stencil import Topology
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
    from gameoflifewithactors_tpu.parallel import sharded

    m = mesh_lib.make_mesh((1, 1), jax.devices()[:1])
    out = {"platform": jax.devices()[0].platform, "cases": []}
    # identity: gun + soup patch, both topologies
    ih, iw, igens = (256, 1024, 24) if _SMOKE else (1024, 4096, 64)
    for topo in (Topology.TORUS, Topology.DEAD):
        grid = np.asarray(seeds.seeded((ih, iw), "gosper_gun",
                                       ih // 4, iw // 4))
        p = bitpack.pack(jnp.asarray(grid))
        tr, tw = auto_tile(ih, iw // 32)
        run = sharded.make_multi_step_packed_sparse_tiled(
            m, CONWAY, topo, tile_rows=tr, tile_words=tw)
        act = sharded.initial_tile_activity(p, m, tr, tw)
        got, _ = run(mesh_lib.device_put_sharded_grid(p, m), act, igens)
        want = multi_step_packed(p, igens, rule=CONWAY, topology=topo)
        same = _device_equal(got, want)
        out["cases"].append({"topology": topo.value, "bit_identical": same})
        if not same:
            out["ok"] = False
            return out

    # rate: 16384² mostly-empty gun (config-#5 shape at bench scale);
    # seeded_packed keeps host work O(pattern), not O(grid)
    side, gens = (2048, 64) if _SMOKE else (16384, 512)
    p = jnp.asarray(seeds.seeded_packed(
        (side, side), "gosper_gun", side // 2, side // 64))
    tr, tw = auto_tile(side, side // 32)
    run = sharded.make_multi_step_packed_sparse_tiled(
        m, CONWAY, Topology.TORUS, tile_rows=tr, tile_words=tw, donate=True)
    act = sharded.initial_tile_activity(p, m, tr, tw)
    p = mesh_lib.device_put_sharded_grid(p, m)
    p, act = run(p, act, 8)
    _sync_scalar(act)
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        p, act = run(p, act, gens)
        _sync_scalar(act)
        best = max(best, gens / (time.perf_counter() - t0))
    out["ok"] = True
    out["gens_per_sec_16384_gun"] = best
    out["active_tiles"] = int(jnp.sum(act))
    out["tile_shape"] = [tr, tw]
    return out


def child_elementary() -> dict:
    """Elementary (1D Wolfram) family natively: numpy brute-force oracle
    for W30/W90/W110 on-chip, then the ensemble rate (8192 universes x
    131072 cells) — the family's first on-chip number."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models.elementary import parse_elementary
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.ops.elementary import multi_step_elementary
    from gameoflifewithactors_tpu.ops.stencil import Topology

    def oracle(row: "np.ndarray", number: int, n: int) -> "np.ndarray":
        for _ in range(n):
            l, r = np.roll(row, 1), np.roll(row, -1)
            row = ((number >> ((l << 2) | (row << 1) | r)) & 1).astype(np.uint8)
        return row

    out = {"platform": jax.devices()[0].platform, "cases": []}
    rng = np.random.default_rng(3)
    for name in ("W30", "W90", "W110"):
        rule = parse_elementary(name)
        row = rng.integers(0, 2, size=256, dtype=np.uint8)
        want = oracle(row.copy(), rule.number, 40)
        got = bitpack.unpack(multi_step_elementary(
            bitpack.pack(jnp.asarray(row[None])), 40, rule=rule,
            topology=Topology.TORUS))[0]
        same = bool(jnp.array_equal(got, jnp.asarray(want)))
        out["cases"].append({"rule": name, "oracle_match": same})
        if not same:
            out["ok"] = False
            return out

    # ensemble rate: independent universes on the leading axis
    H, W, gens = (256, 4096, 64) if _SMOKE else (8192, 131072, 512)
    p = jnp.asarray(rng.integers(0, 2 ** 32, size=(H, W // 32), dtype=np.uint32))
    rule = parse_elementary("W30")
    p = multi_step_elementary(p, 8, rule=rule)
    _sync_scalar(p)
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        p = multi_step_elementary(p, gens, rule=rule)
        _sync_scalar(p)
        best = max(best, H * W * gens / (time.perf_counter() - t0))
    out["ok"] = True
    out["cell_updates_per_sec"] = best
    return out


def child_config5_sparse() -> dict:
    out_path = os.path.join(_REPO, "results", "config5_sparse_65536_tpu.json")
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "config5_sparse.py"),
         "--gens", "256", "--repeats", "2", "--chunk-ab", "--out", out_path],
        capture_output=True, text=True, timeout=WATCHDOG_S)
    line = next((ln for ln in reversed(r.stdout.strip().splitlines())
                 if ln.startswith("{")), None)
    if r.returncode or line is None:
        return {"ok": False, "detail": (r.stderr or r.stdout)[-800:]}
    return {"ok": True, **json.loads(line)}


ITEMS = {
    "bench_packed": child_bench_packed,
    "pallas_identity": child_pallas_identity,
    "pallas_autotune": child_pallas_autotune,
    "ltl_bosco": child_ltl_bosco,
    "generations_brain": child_generations_brain,
    "ltl_lowering": child_ltl_lowering,
    "pallas_band": child_pallas_band,
    "pallas_generations": child_pallas_generations,
    "profile_trace": child_profile_trace,
    "ltl_pallas": child_ltl_pallas,
    "ltl_planes": child_ltl_planes,
    "sparse_tiled": child_sparse_tiled,
    "elementary": child_elementary,
    "config5_sparse": child_config5_sparse,
}

# bench_packed / config5_sparse already run their body in a subprocess of
# their own; the rest run jax in THIS process when invoked with --item
_INPROC_ITEMS = [k for k in ITEMS if k not in ("bench_packed", "config5_sparse")]


def _provenance():
    """Load utils/provenance.py WITHOUT the package __init__ (which imports
    jax — a hang when the tunnel is wedged; this parent must stay jax-free)."""
    import importlib.util

    path = os.path.join(_REPO, "gameoflifewithactors_tpu", "utils", "provenance.py")
    spec = importlib.util.spec_from_file_location("_worklist_provenance", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _merge(item: str, result: dict) -> None:
    try:
        with open(OUT_PATH) as f:
            store = json.load(f)
    except (OSError, json.JSONDecodeError):
        store = {}
    prev = store.get(item)
    # keep a previous ok result over a new failure; otherwise replace
    if not (prev and prev.get("ok") and not result.get("ok")):
        # stamp ONLY results without their own provenance: a result that
        # already carries a commit (e.g. a persisted bench record) keeps it
        # whole — re-stamping would launder old evidence as HEAD's, and
        # mixing (their commit + our commit_dirty) would brand a clean
        # measurement with this process's dirty tree. The stamp embeds the
        # item's measured file set (provenance.ITEM_PATHS) so the record
        # self-describes what it measured and unrelated CPU-side edits
        # can't stale it later (VERDICT r4 Weak #1).
        prov = _provenance()
        stamp = ({} if "commit" in result
                 else {**prov.head_stamp(paths=prov.ITEM_PATHS.get(item)),
                       # self-identify: staleness() scopes the worklist
                       # protocol file to this item's child function even
                       # when the caller can't pass item=
                       "worklist_item": item})
        store[item] = {**stamp, **result,
                       "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    tmp = OUT_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(store, f, indent=1)
        f.write("\n")
    os.replace(tmp, OUT_PATH)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--items", default=",".join(ITEMS))
    ap.add_argument("--force", action="store_true", help="skip the probe gate")
    ap.add_argument("--item", help=argparse.SUPPRESS)  # child mode
    args = ap.parse_args()

    if args.item:
        print(json.dumps(ITEMS[args.item]()))
        return 0

    if not args.force:
        from tpu_probe import probe

        health = probe(timeout=float(os.environ.get("TPU_PROBE_TIMEOUT_S", "60")))
        print(f"tpu_probe: {health['status']} ({health['detail']})", file=sys.stderr)
        if health["status"] != "healthy":
            print(json.dumps({"skipped": True, "probe": health}))
            return 1

    failures = 0
    for item in args.items.split(","):
        item = item.strip()
        if item not in ITEMS:
            raise SystemExit(f"unknown item {item!r}; know {sorted(ITEMS)}")
        t0 = time.perf_counter()
        if item in _INPROC_ITEMS:
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__), "--item", item],
                    capture_output=True, text=True, timeout=_watchdog_for(item))
                line = next((ln for ln in reversed(r.stdout.strip().splitlines())
                             if ln.startswith("{")), None)
                result = (json.loads(line) if r.returncode == 0 and line
                          else {"ok": False, "detail": (r.stderr or r.stdout)[-800:]})
            except subprocess.TimeoutExpired:
                result = {"ok": False,
                          "detail": f"hung >{_watchdog_for(item)}s (wedged?)"}
        else:
            try:
                result = ITEMS[item]()
            except subprocess.TimeoutExpired:
                result = {"ok": False,
                          "detail": f"hung >{_watchdog_for(item)}s (wedged?)"}
        result["elapsed_s"] = round(time.perf_counter() - t0, 1)
        if result.get("ok") and result.get("platform") == "cpu":
            # a --force run on a TPU-less interpreter (or a CPU-fallback
            # jax init) must not merge as captured TPU evidence — the
            # watcher would count the item done and stop recapturing it
            # (the same guard child_bench_packed applies to its bench line)
            result = {**result, "ok": False,
                      "detail": "ran on the cpu platform; not TPU evidence"}
        _merge(item, result)
        print(f"{item}: {'ok' if result.get('ok') else 'FAILED'} "
              f"({result['elapsed_s']}s)", file=sys.stderr)
        failures += 0 if result.get("ok") else 1
    print(json.dumps({"done": True, "failures": failures, "out": OUT_PATH}))
    return 0 if failures == 0 else 2


if __name__ == "__main__":
    sys.exit(main())
