"""Harness plumbing: tpu_probe classification + bench persistence.

These protect the round-1 lesson (VERDICT.md Weak #2): a wedged tunnel at
end-of-round must degrade to a classified probe status and a persisted
earlier TPU measurement, not a 420s hang plus a silent CPU number.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import axon_guard  # noqa: E402
import tpu_probe  # noqa: E402


def _cpu_env():
    return {**os.environ, "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": axon_guard.strip_pythonpath(),
            "XLA_FLAGS": ""}


def test_probe_classifies_cpu_only():
    r = tpu_probe.probe(timeout=120.0, env=_cpu_env())
    assert r["status"] == "cpu-only"
    assert r["platform"] == "cpu"
    assert r["stages"][-1].startswith("compute-done")


def test_probe_classifies_a_hang(monkeypatch):
    # a child that stalls mid-init must be classified, not waited on forever
    monkeypatch.setattr(
        tpu_probe, "_CHILD",
        'import sys, time\n'
        'sys.stdout.write("STAGE import-start\\n"); sys.stdout.flush()\n'
        'sys.stdout.write("STAGE import-done\\n"); sys.stdout.flush()\n'
        'time.sleep(3600)\n')
    r = tpu_probe.probe(timeout=3.0, env=_cpu_env())
    assert r["status"] == "wedged-init"
    assert r["stages"] == ["import-start", "import-done"]


def test_probe_classifies_child_error(monkeypatch):
    monkeypatch.setattr(
        tpu_probe, "_CHILD",
        'import sys\n'
        'sys.stdout.write("STAGE import-start\\n"); sys.stdout.flush()\n'
        'raise RuntimeError("pjrt init failed")\n')
    r = tpu_probe.probe(timeout=30.0, env=_cpu_env())
    assert r["status"] == "error"
    assert "pjrt init failed" in r["detail"]


def test_probe_cli_json():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tpu_probe.py"),
         "--timeout", "120", "--json"],
        capture_output=True, text=True, env=_cpu_env(), timeout=180)
    out = json.loads(r.stdout)
    assert out["status"] == "cpu-only"
    assert r.returncode == 1  # healthy (real TPU) is the only rc-0 state


def test_bench_persistence_round_trip(tmp_path, monkeypatch):
    import bench

    monkeypatch.setattr(bench, "PERSIST_PATH", str(tmp_path / "tpu_best.json"))
    key = "packed:default:B3/S23"
    assert bench._load_persisted(key) is None

    bench._persist_if_best(key, {"metric": "m (axon)", "value": 2e9,
                                 "unit": "cell-updates/sec", "vs_baseline": 2.0})
    got = bench._load_persisted(key)
    assert got["value"] == 2e9
    assert "recorded_at" in got

    # a worse later measurement must not clobber the best one
    bench._persist_if_best(key, {"metric": "m (axon)", "value": 1e9,
                                 "unit": "cell-updates/sec", "vs_baseline": 1.0})
    assert bench._load_persisted(key)["value"] == 2e9

    # a better one replaces it; other keys are untouched
    bench._persist_if_best(key, {"metric": "m (axon)", "value": 3e9,
                                 "unit": "cell-updates/sec", "vs_baseline": 3.0})
    bench._persist_if_best("sparse:65536:B3/S23", {"metric": "s", "value": 1.0,
                                                   "unit": "u", "vs_baseline": 0.0})
    assert bench._load_persisted(key)["value"] == 3e9
    assert bench._load_persisted("sparse:65536:B3/S23")["value"] == 1.0


def test_bench_persisted_alternate_backend_matching(tmp_path, monkeypatch):
    """An 'auto' request may use any resolved-backend record; an explicit
    request may use an 'auto' record ONLY when that run resolved to the
    requested backend (the metric string names it) — a pallas number must
    never stand in for a --backend dense measurement."""
    import bench

    monkeypatch.setattr(bench, "PERSIST_PATH", str(tmp_path / "tpu_best.json"))
    bench._persist_if_best("auto:default:B3/S23", {
        "metric": "cell-updates/sec/chip, 16384x16384 B3/S23 (pallas, 50% soup, tpu)",
        "value": 1.3e12, "unit": "cell-updates/sec", "vs_baseline": 1300.0})

    assert bench._load_persisted("pallas:default:B3/S23")["value"] == 1.3e12
    assert bench._load_persisted("dense:default:B3/S23") is None
    assert bench._load_persisted("packed:default:B3/S23") is None

    bench._persist_if_best("packed:default:B3/S23", {
        "metric": "cell-updates/sec/chip, 16384x16384 B3/S23 (packed, 50% soup, tpu)",
        "value": 1.7e11, "unit": "cell-updates/sec", "vs_baseline": 170.0})
    # auto prefers the best across resolved records
    assert bench._load_persisted("auto:default:B3/S23")["value"] == 1.3e12


def test_bench_config_key_uses_requested_size():
    import bench

    a = bench._parse(["--backend", "packed"])
    b = bench._parse(["--backend", "packed", "--size", "16384"])
    assert bench._config_key(a) == "packed:default:B3/S23"
    assert bench._config_key(b) == "packed:16384:B3/S23"
    assert bench._config_key(a) != bench._config_key(b)


def test_bench_report_scoreboard():
    """`bench.py --report` prints the provenance scoreboard without
    importing jax (must work while the tunnel is wedged) and ends with a
    machine-readable JSON summary line."""
    import json
    import sys

    import axon_guard

    env = {**os.environ, "PYTHONPATH": axon_guard.strip_pythonpath()}
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"),
                        "--report"],
                       capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr[-600:]
    last = r.stdout.strip().splitlines()[-1]
    d = json.loads(last)
    assert d["report"] is True and d["records"] >= 1
    # the committed store always has the headline auto record
    assert any(ln.split()[2] == "auto:default:B3/S23"
               for ln in r.stdout.splitlines() if ln.startswith(("FRESH", "stale")))
    # standalone artifacts (config5 captures etc.) are on the scoreboard
    # too — one glance covers ALL persisted evidence, not just the stores
    assert any(ln.split()[1] == "artifact"
               for ln in r.stdout.splitlines()
               if ln.startswith(("FRESH", "stale", "FAILED")))


def test_worklist_children_smoke_cpu():
    """The round-3 worklist children (sparse_tiled, elementary) validated
    end-to-end on CPU at WORKLIST_SMOKE=1 scale — a regression (bad
    import, shape bug) must surface here, not on the next healthy tunnel
    window."""
    import json
    import os
    import sys

    import axon_guard

    # children must not see the axon plugin path: its sitecustomize imports
    # jax at interpreter startup and a wedged tunnel hangs the discovery
    # (the same reason bench.py strips it for its CPU fallback child)
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "WORKLIST_SMOKE": "1",
           "PYTHONPATH": axon_guard.strip_pythonpath()}
    # ltl_pallas also has a smoke mode but its interpret-grade radius-5
    # kernel runs >7 min on this host — validated by test_pallas.py's
    # interpret cases instead of here
    for item in ("sparse_tiled", "elementary", "profile_trace",
                 "ltl_planes", "pallas_generations"):
        r = subprocess.run(
            [sys.executable, "scripts/tpu_worklist.py", "--item", item],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        line = next((ln for ln in reversed(r.stdout.strip().splitlines())
                     if ln.startswith("{")), None)
        assert r.returncode == 0 and line, (item, r.stderr[-600:])
        d = json.loads(line)
        assert d.get("ok") is True, (item, d)
        if item == "profile_trace":
            # perfetto capture + parse ran; CPU has no device tracks, but
            # the host python track must have recorded real slices
            assert d["trace_bytes"] > 0 and "perfetto" in d, d
            assert d["perfetto"]["tracks"], d["perfetto"]
        else:
            assert all(c.get("bit_identical", c.get("oracle_match"))
                       for c in d["cases"]), (item, d["cases"])


def test_weak_scaling_script_end_to_end():
    # VERDICT round-1 #8: the harness must be proven runnable; tiny config
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "weak_scaling.py"),
         "--counts", "1,2", "--tile", "64x64", "--gens", "4", "--repeats", "1"],
        capture_output=True, text=True, timeout=240,
        env={**_cpu_env(), "XLA_FLAGS": "--xla_force_host_platform_device_count=2"})
    assert r.returncode == 0, r.stderr[-1500:]
    lines = [json.loads(ln) for ln in r.stdout.strip().splitlines()]
    assert lines[0]["devices"] == 1 and lines[0]["weak_scaling_efficiency"] == 1.0
    assert lines[1]["devices"] == 2 and lines[1]["cell_updates_per_sec"] > 0
    assert lines[-1]["unit"] == "fraction"


def test_watcher_items_match_worklist_registry():
    """A typo in tpu_watch.sh's ITEMS list would crash the capture loop at
    the next healthy window ('unknown item' SystemExit) — the most
    expensive possible place to discover it. Pin the list against the
    orchestrator's registry, and require the two never-natively-compiled
    kernels to burn the FRONT of the window (VERDICT r3 directive #1)."""
    import os
    import re
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "scripts"))
    import tpu_worklist

    sh = open(os.path.join(repo, "scripts", "tpu_watch.sh")).read()
    m = re.search(r"^ITEMS=([a-z0-9_,]+)$", sh, re.MULTILINE)
    assert m, "tpu_watch.sh must define ITEMS=<comma list>"
    items = m.group(1).split(",")
    unknown = [i for i in items if i not in tpu_worklist.ITEMS]
    assert not unknown, f"watcher ITEMS not in the worklist registry: {unknown}"
    missing = sorted(set(tpu_worklist.ITEMS) - set(items))
    assert not missing, f"worklist items the watcher never captures: {missing}"
    assert items.index("pallas_generations") < 3
    assert items.index("ltl_pallas") < 3


def test_roofline_report_renders_from_trace_record():
    """scripts/roofline_report.py turns a profile_trace capture into the
    publishable measured-roofline markdown (VERDICT r4 #3) and refuses
    unusable records — exercised on a synthetic record in the exact shape
    child_profile_trace writes."""
    import importlib.util
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "roofline_report", os.path.join(repo, "scripts", "roofline_report.py"))
    rr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rr)

    rec = {
        "ok": True, "platform": "tpu", "commit": "abc1234",
        "recorded_at": "2026-08-01T00:00:00Z",
        "dispatch": {"rows": 4096, "words": 512, "gens": 64,
                     "cell_updates": 4096 * 512 * 32 * 64},
        "measured_in_kernel_rate": 2.5e12,
        "measured_duty_cycle": 0.9,
        "perfetto": {
            "device_track": "/device:TPU:0/XLA Ops",
            "device_busy_us": 1718.0, "device_span_us": 1909.0,
            "tracks": [{"track": "/device:TPU:0/XLA Ops",
                        "busy_us": 1718.0, "span_us": 1909.0,
                        "n_slices": 70,
                        "top": [["fused_multi_step", 1500.0],
                                ["copy-start", 100.0]]}],
        },
    }
    md = rr.render_roofline({"profile_trace": rec}, {
        "auto:default:B3/S23": {"value": 2.2e12}})
    assert md is not None and md.startswith("## Measured roofline")
    assert "2.5e+12" in md and "90.0%" in md
    assert "fused_multi_step" in md and "copy-start" in md
    assert "2.2e+12" in md  # headline quoted for the in-kernel-vs-bench gap

    # unusable records refuse: cpu platform, missing perfetto, not ok
    assert rr.render_roofline({"profile_trace": {**rec, "platform": "cpu"}},
                              {}) is None
    assert rr.render_roofline({"profile_trace": {**rec, "ok": False}}, {}) is None
    bad = {**rec}
    bad.pop("perfetto")
    assert rr.render_roofline({"profile_trace": bad}, {}) is None
    assert rr.render_roofline({}, {}) is None


def test_worklist_merge_embeds_measured_paths(tmp_path, monkeypatch):
    """_merge stamps new records with the item's measured file set so they
    self-describe (round-5 provenance precision); results that carry their
    own commit are kept whole."""
    import importlib.util
    import json
    import sys

    spec = importlib.util.spec_from_file_location(
        "tpu_worklist_merge_test", os.path.join(REPO, "scripts", "tpu_worklist.py"))
    wl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(wl)
    out = tmp_path / "worklist.json"
    monkeypatch.setattr(wl, "OUT_PATH", str(out))

    wl._merge("pallas_identity", {"ok": True, "platform": "t"})
    rec = json.loads(out.read_text())["pallas_identity"]
    assert rec["measured_paths"] == wl._provenance().ITEM_PATHS["pallas_identity"]
    assert "gameoflifewithactors_tpu/ops/sparse.py" not in rec["measured_paths"]

    # a result with its own provenance is not re-stamped
    wl._merge("bench_packed", {"ok": True, "commit": "deadbee", "value": 1})
    rec = json.loads(out.read_text())["bench_packed"]
    assert rec["commit"] == "deadbee" and "measured_paths" not in rec


def test_bench_attribution_pointer_and_path_rule(tmp_path, monkeypatch):
    """A profiler-armed measurement's op-class attribution rides next to
    its RunReport pointer (ISSUE 18): `profile_attribution` appears in
    the persisted record iff the sibling file exists, repo-relative like
    `telemetry_report`; and the parent's jax-free `_attribution_path`
    mirror agrees with obs.profiler.attribution_path_for byte for byte."""
    import bench
    from gameoflifewithactors_tpu.obs.profiler import attribution_path_for

    for p in ("results/run.json", "a/b.json", "noext"):
        assert bench._attribution_path(p) == attribution_path_for(p)

    monkeypatch.setattr(bench, "PERSIST_PATH",
                        str(tmp_path / "results" / "tpu_best.json"))
    report = tmp_path / "results" / "bench_report_k.json"
    report.parent.mkdir(parents=True)
    report.write_text("{}")
    rec = {"metric": "m (packed, 50% soup, tpu)", "value": 1e9,
           "unit": "cell-updates/sec", "vs_baseline": 1.0}
    # no attribution sibling: only the report pointer appears
    bench._persist_if_best("packed:default:B3/S23", rec,
                           report_path=str(report))
    got = bench._load_persisted("packed:default:B3/S23")
    assert got["telemetry_report"] == "results/bench_report_k.json"
    assert "profile_attribution" not in got
    # armed measurement: the sibling exists and the pointer rides along
    (tmp_path / "results" / "bench_report_k.attribution.json").write_text(
        '{"windows": 1}')
    bench._persist_if_best("packed:default:B3/S23", {**rec, "value": 2e9},
                           report_path=str(report))
    got = bench._load_persisted("packed:default:B3/S23")
    assert got["profile_attribution"] == \
        "results/bench_report_k.attribution.json"
