"""Batch (DP) axis: sharded batch-of-universes matches per-universe runs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gameoflifewithactors_tpu.models.rules import CONWAY, HIGHLIFE
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.packed import multi_step_packed
from gameoflifewithactors_tpu.ops.stencil import Topology
from gameoflifewithactors_tpu.parallel import batched


@pytest.mark.parametrize("mesh_shape", [(2, 2, 2), (1, 2, 4), (4, 1, 2)])
def test_batched_bit_identity(mesh_shape):
    rng = np.random.default_rng(77)
    B = 4
    grids = rng.integers(0, 2, size=(B, 16, 128), dtype=np.uint8)
    packed = jnp.stack([bitpack.pack(jnp.asarray(g)) for g in grids])

    mesh = batched.make_batch_mesh(mesh_shape)
    sharded_in = jax.device_put(packed, batched.batch_sharding(mesh))
    run = batched.make_multi_step_packed_batched(mesh, CONWAY, Topology.TORUS)
    out = run(sharded_in, 6)

    for i in range(B):
        want = multi_step_packed(
            bitpack.pack(jnp.asarray(grids[i])), 6, rule=CONWAY, topology=Topology.TORUS
        )
        np.testing.assert_array_equal(
            np.asarray(bitpack.unpack(out[i])), np.asarray(bitpack.unpack(want)),
            err_msg=f"universe {i} diverged",
        )


def test_batched_dead_topology():
    rng = np.random.default_rng(3)
    grids = rng.integers(0, 2, size=(2, 8, 64), dtype=np.uint8)
    packed = jnp.stack([bitpack.pack(jnp.asarray(g)) for g in grids])
    mesh = batched.make_batch_mesh((2, 2, 2))
    run = batched.make_multi_step_packed_batched(mesh, HIGHLIFE, Topology.DEAD)
    out = run(jax.device_put(packed, batched.batch_sharding(mesh)), 3)
    for i in range(2):
        want = multi_step_packed(
            bitpack.pack(jnp.asarray(grids[i])), 3, rule=HIGHLIFE, topology=Topology.DEAD
        )
        np.testing.assert_array_equal(
            np.asarray(bitpack.unpack(out[i])), np.asarray(bitpack.unpack(want))
        )


def test_batch_mesh_validation():
    with pytest.raises(ValueError):
        batched.make_batch_mesh((3, 2, 2))


@pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
@pytest.mark.parametrize("mesh_shape,B,H,g", [
    ((2, 4, 1), 4, 32, 3),   # 2 universes/device, 8-row bands
    ((4, 2, 1), 4, 64, 8),   # 1 universe/device, 32-row bands
    ((2, 2, 2), 4, 32, 3),   # 2D spatial submesh: flattened into 4 bands
])
def test_batched_pallas_band_bit_identity(mesh_shape, B, H, g, topology):
    """DP x row-band native-kernel composition (interpret mode): every
    universe must match its own single-device packed evolution — DEAD
    exercises the SMEM edge-code exterior re-zero through the DP stack;
    (nb, nx, ny > 1) meshes flatten the spatial axes into nx*ny bands."""
    rng = np.random.default_rng(31)
    grids = rng.integers(0, 2, size=(B, H, 64), dtype=np.uint8)
    packed = jnp.stack([bitpack.pack(jnp.asarray(u)) for u in grids])

    mesh = batched.make_batch_mesh(mesh_shape)
    sharding = (batched.batch_band_sharding(mesh) if mesh_shape[2] > 1
                else batched.batch_sharding(mesh))
    run = batched.make_multi_step_pallas_batched(
        mesh, CONWAY, topology=topology, gens_per_exchange=g, interpret=True)
    out = run(jax.device_put(packed, sharding), 2)
    for i in range(B):
        want = multi_step_packed(packed[i], 2 * g, rule=CONWAY,
                                 topology=topology)
        np.testing.assert_array_equal(
            np.asarray(out[i]), np.asarray(want),
            err_msg=f"universe {i} diverged on mesh {mesh_shape}")


def test_batched_masked_freezes_slots():
    """masked=True: the occupancy mask is a *runtime operand* — mask-0
    slots pass through bit-identical while mask-1 slots advance. This is
    the invariant the serving lanes (serve/lanes.py) multiplex sessions
    on: claiming/freeing a slot never changes the jit signature."""
    rng = np.random.default_rng(9)
    B = 4
    grids = rng.integers(0, 2, size=(B, 16, 64), dtype=np.uint8)
    packed = jnp.stack([bitpack.pack(jnp.asarray(g)) for g in grids])
    mesh = batched.make_batch_mesh((1, 1, 1), devices=jax.devices()[:1])
    run = batched.make_multi_step_packed_batched(
        mesh, CONWAY, Topology.TORUS, masked=True)
    mask = np.array([1, 0, 1, 0], dtype=np.uint32)
    out = run(np.asarray(packed), 4, mask)
    for i in range(B):
        want = (multi_step_packed(packed[i], 4, rule=CONWAY,
                                  topology=Topology.TORUS)
                if mask[i] else packed[i])
        np.testing.assert_array_equal(
            np.asarray(out[i]), np.asarray(want),
            err_msg=f"slot {i} (mask {mask[i]})")
    # flipping the mask re-dispatches the same executable (operand, not
    # signature): all-frozen passes the whole batch through untouched
    out2 = run(np.asarray(packed), 4, np.zeros(B, dtype=np.uint32))
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(packed))


def test_batched_pallas_masked_freezes_slots():
    """The masked contract through the native-kernel DP runner
    (interpret mode): the select is applied per chunk, after the kernel,
    so frozen slots never drift even though their bands still flow
    through the DMA pipeline."""
    rng = np.random.default_rng(10)
    B = 2
    grids = rng.integers(0, 2, size=(B, 16, 64), dtype=np.uint8)
    packed = jnp.stack([bitpack.pack(jnp.asarray(g)) for g in grids])
    mesh = batched.make_batch_mesh((1, 1, 1), devices=jax.devices()[:1])
    run = batched.make_multi_step_pallas_batched(
        mesh, CONWAY, Topology.TORUS, gens_per_exchange=2, masked=True,
        interpret=True)
    mask = np.array([0, 1], dtype=np.uint32)
    out = run(np.asarray(packed), 1, mask)  # one chunk = 2 generations
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(packed[0]))
    want = multi_step_packed(packed[1], 2, rule=CONWAY,
                             topology=Topology.TORUS)
    np.testing.assert_array_equal(np.asarray(out[1]), np.asarray(want))
