"""Batch (DP) axis: sharded batch-of-universes matches per-universe runs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gameoflifewithactors_tpu.models.rules import CONWAY, HIGHLIFE
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.packed import multi_step_packed
from gameoflifewithactors_tpu.ops.stencil import Topology
from gameoflifewithactors_tpu.parallel import batched


@pytest.mark.parametrize("mesh_shape", [(2, 2, 2), (1, 2, 4), (4, 1, 2)])
def test_batched_bit_identity(mesh_shape):
    rng = np.random.default_rng(77)
    B = 4
    grids = rng.integers(0, 2, size=(B, 16, 128), dtype=np.uint8)
    packed = jnp.stack([bitpack.pack(jnp.asarray(g)) for g in grids])

    mesh = batched.make_batch_mesh(mesh_shape)
    sharded_in = jax.device_put(packed, batched.batch_sharding(mesh))
    run = batched.make_multi_step_packed_batched(mesh, CONWAY, Topology.TORUS)
    out = run(sharded_in, 6)

    for i in range(B):
        want = multi_step_packed(
            bitpack.pack(jnp.asarray(grids[i])), 6, rule=CONWAY, topology=Topology.TORUS
        )
        np.testing.assert_array_equal(
            np.asarray(bitpack.unpack(out[i])), np.asarray(bitpack.unpack(want)),
            err_msg=f"universe {i} diverged",
        )


def test_batched_dead_topology():
    rng = np.random.default_rng(3)
    grids = rng.integers(0, 2, size=(2, 8, 64), dtype=np.uint8)
    packed = jnp.stack([bitpack.pack(jnp.asarray(g)) for g in grids])
    mesh = batched.make_batch_mesh((2, 2, 2))
    run = batched.make_multi_step_packed_batched(mesh, HIGHLIFE, Topology.DEAD)
    out = run(jax.device_put(packed, batched.batch_sharding(mesh)), 3)
    for i in range(2):
        want = multi_step_packed(
            bitpack.pack(jnp.asarray(grids[i])), 3, rule=HIGHLIFE, topology=Topology.DEAD
        )
        np.testing.assert_array_equal(
            np.asarray(bitpack.unpack(out[i])), np.asarray(bitpack.unpack(want))
        )


def test_batch_mesh_validation():
    with pytest.raises(ValueError):
        batched.make_batch_mesh((3, 2, 2))
