"""The run-telemetry subsystem (obs/): spans, compile events, watchdog,
RunReport — the layer every perf/robustness claim reports through.

Covers the ISSUE-1 acceptance points: span nesting/threading and the
chrome-trace/JSONL exports; compile-event capture at the ops/_jit choke
point; the stall watchdog firing on a wedged tick and naming the
last-completed span; RunReport JSON round-trip; and the regression that
a tick's ``StepMetrics.wall_seconds`` excludes the compile time the
same tick paid (the first-tick 400x mirage).
"""

import io
import json
import threading
import time

import pytest

from gameoflifewithactors_tpu.obs import compile as obs_compile
from gameoflifewithactors_tpu.obs import report as report_lib
from gameoflifewithactors_tpu.obs import spans as spans_lib
from gameoflifewithactors_tpu.obs import watchdog as watchdog_lib
from gameoflifewithactors_tpu.obs.registry import MetricsRegistry
from gameoflifewithactors_tpu.obs.report import RunReport, begin_run_telemetry
from gameoflifewithactors_tpu.obs.spans import SpanTracer
from gameoflifewithactors_tpu.obs.watchdog import StallWatchdog


# -- pillar 1: the span tracer ------------------------------------------------


def test_span_nesting_depth_and_phase_totals():
    tr = SpanTracer()
    with tr.span("outer", layer="coordinator"):
        with tr.span("inner"):
            pass
        with tr.span("inner"):
            pass
    spans = tr.spans()
    assert [s.name for s in spans] == ["inner", "inner", "outer"]  # completion order
    assert [s.depth for s in spans] == [1, 1, 0]
    assert spans[-1].attrs == {"layer": "coordinator"}
    assert all(s.t1 >= s.t0 for s in spans)
    phases = tr.phase_seconds()
    assert phases["inner"]["count"] == 2
    # nested spans each count their own wall time: outer covers both inners
    assert phases["outer"]["total_s"] >= phases["inner"]["total_s"]
    assert tr.last_completed().name == "outer"


def test_span_thread_safety_and_per_thread_stacks():
    tr = SpanTracer()
    n, per = 8, 50
    barrier = threading.Barrier(n)

    def work(i):
        barrier.wait()
        for _ in range(per):
            with tr.span(f"t{i}", worker=i):
                # nesting is per-thread: another thread's open span must
                # not appear in this thread's stack
                assert tr.current_stack() == [f"t{i}"]
    threads = [threading.Thread(target=work, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = tr.spans()
    assert len(spans) == n * per
    assert all(s.depth == 0 for s in spans)


def test_span_ring_buffer_bounds_memory():
    tr = SpanTracer(maxlen=16)
    for i in range(100):
        with tr.span(f"s{i}"):
            pass
    spans = tr.spans()
    assert len(spans) == 16
    assert spans[-1].name == "s99"


def test_chrome_trace_and_jsonl_exports(tmp_path):
    tr = SpanTracer()
    with tr.span("a", k=1):
        with tr.span("b"):
            pass
    path = tr.write_chrome_trace(str(tmp_path / "trace.json"))
    doc = json.load(open(path))
    events = doc["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"a", "b"}
    assert all(e["dur"] >= 0 and e["ts"] > 0 for e in xs)
    # thread metadata present, so perfetto labels the host track
    assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in events)

    buf = io.StringIO()
    tr.write_jsonl(buf)
    lines = [json.loads(l) for l in buf.getvalue().splitlines()]
    assert [l["name"] for l in lines] == ["b", "a"]
    assert all("seconds" in l for l in lines)


# -- pillar 2: compile events + registry --------------------------------------


def test_tracked_call_records_compile_once(cold_compile_cache):
    import jax.numpy as jnp

    from gameoflifewithactors_tpu.ops._jit import optionally_donated

    @optionally_donated("p", static=())
    def _obs_probe(p):
        return p + 1

    log = obs_compile.CompileEventLog()
    x = jnp.zeros((4, 4), jnp.uint32)
    for _ in range(3):
        obs_compile.tracked_call(_obs_probe.jitted, "_obs_probe", (x,), {},
                                 log=log)
    events = log.events()
    assert len(events) == 1  # first call compiled; the rest were cache hits
    ev = events[0]
    assert ev.runner == "_obs_probe" and ev.cache_miss
    assert "uint32[4,4]" in ev.signature
    assert ev.wall_seconds > 0
    # a new shape is a new trace: one more event, attributable by window
    t_before = time.perf_counter()
    obs_compile.tracked_call(_obs_probe.jitted, "_obs_probe",
                             (jnp.zeros((8, 8), jnp.uint32),), {}, log=log)
    t_after = time.perf_counter()
    assert len(log.events()) == 2
    assert log.compile_seconds_between(t_before, t_after) == pytest.approx(
        log.events()[-1].wall_seconds)
    assert log.total_compile_seconds() == pytest.approx(
        sum(e.wall_seconds for e in log.events()))


def test_engine_step_emits_compile_event(cold_compile_cache):
    """The jit entry points in ops/_jit.py are the choke point: stepping a
    fresh (shape, rule) through the engine must leave a CompileEvent in
    the global log, naming the runner."""
    from gameoflifewithactors_tpu.coordinator import GridCoordinator

    obs_compile.COMPILE_LOG.clear()
    eng = GridCoordinator((56, 64), "B3/S23", random_fill=0.4,
                          backend="packed").engine
    eng.step(2)
    eng.block_until_ready()
    misses = [e for e in obs_compile.COMPILE_LOG.events() if e.cache_miss]
    assert misses, "first step of a fresh shape must record a compile"
    assert any("multi_step" in e.runner for e in misses)


def test_registry_concurrent_reads_and_writes_hammer():
    """ISSUE-3 lock audit: value()/snapshot() used to read ``_series``
    without the lock while writers mutate it — under enough label churn
    a reader could hit a resizing dict (RuntimeError) or a torn view.
    Hammer every instrument from writer threads while reader threads
    spin on value()/snapshot(); then verify exact totals (no lost
    updates) and that no reader ever raised."""
    reg = MetricsRegistry()
    n_writers, per = 8, 400
    errors = []
    stop = threading.Event()
    barrier = threading.Barrier(n_writers + 2)

    def writer(i):
        barrier.wait()
        for j in range(per):
            # fresh label values force dict *growth*, the resize case
            reg.counter("hammer_evs").inc(worker=i)
            reg.counter("hammer_evs").inc(worker=i, batch=j % 17)
            reg.gauge("hammer_depth").set(j, worker=i)
            reg.histogram("hammer_secs").observe(j * 1e-4, worker=i)

    def reader():
        barrier.wait()
        while not stop.is_set():
            try:
                for i in range(n_writers):
                    reg.counter("hammer_evs").value(worker=i)
                    reg.gauge("hammer_depth").value(worker=i)
                snap = reg.snapshot()
                for inst in snap.values():
                    sum(s.get("value", 0) for s in inst.get("series", []))
            except Exception as exc:  # pragma: no cover - the regression
                errors.append(exc)
                return

    threads = ([threading.Thread(target=writer, args=(i,))
                for i in range(n_writers)]
               + [threading.Thread(target=reader) for _ in range(2)])
    for t in threads:
        t.start()
    for t in threads[:n_writers]:
        t.join()
    stop.set()
    for t in threads[n_writers:]:
        t.join()
    assert not errors, f"reader raced a writer: {errors[:1]}"
    for i in range(n_writers):
        assert reg.counter("hammer_evs").value(worker=i) == per
    snap = reg.snapshot()
    assert sum(s["value"] for s in snap["hammer_evs"]["series"]) == \
        n_writers * per * 2
    hist = snap["hammer_secs"]["series"]
    assert sum(s["n"] for s in hist) == n_writers * per


def test_registry_instruments():
    reg = MetricsRegistry()
    reg.counter("evs").inc(runner="a")
    reg.counter("evs").inc(2.5, runner="a")
    reg.counter("evs").inc(runner="b")
    assert reg.counter("evs").value(runner="a") == 3.5
    with pytest.raises(ValueError):
        reg.counter("evs").inc(-1)
    reg.gauge("depth").set(7, q="x")
    assert reg.gauge("depth").value(q="x") == 7
    h = reg.histogram("secs")
    for v in (0.0005, 0.05, 5.0):
        h.observe(v)
    snap = reg.snapshot()
    assert snap["secs"]["series"][0]["n"] == 3
    assert snap["secs"]["series"][0]["sum"] == pytest.approx(5.0505)
    with pytest.raises(ValueError):
        reg.gauge("evs")  # name already registered as a counter


# -- pillar 3: the stall watchdog ---------------------------------------------


def test_watchdog_fires_on_stalled_tick_and_names_last_span():
    """The wedged-probe diagnostic: a tick that sleeps past the deadline
    is flagged *while still stuck*, with the last-completed span named."""
    tr = SpanTracer()
    stalls = []
    wd = StallWatchdog(0.08, tracer=tr, on_stall=stalls.append)
    with wd:
        with tr.span("engine.step"):
            pass
        with wd.watch("tick@gen0+1"):
            with tr.span("engine.sync"):
                deadline = time.perf_counter() + 2.0
                while not stalls and time.perf_counter() < deadline:
                    time.sleep(0.01)  # the wedge: sync never returns
    assert len(stalls) == 1, "exactly one event per stalled tick"
    ev = stalls[0]
    assert ev.label == "tick@gen0+1"
    assert ev.last_completed_span == "engine.step"
    assert ev.elapsed_seconds > ev.deadline_seconds == pytest.approx(0.08)
    assert ev.open_spans == ("engine.sync",)
    assert wd.events == stalls


def test_watchdog_check_is_deterministic():
    """_check drives detection without racing the monitor thread."""
    tr = SpanTracer()
    wd = StallWatchdog(1.0, tracer=tr, on_stall=lambda ev: None)
    with wd.watch("tick"):
        t0 = wd._active[1]
        assert wd._check(t0 + 0.5) is None          # within deadline
        ev = wd._check(t0 + 1.5)                     # past deadline
        assert ev is not None and ev.label == "tick"
        assert wd._check(t0 + 2.0) is None           # one event per tick
    assert wd._check(time.perf_counter()) is None    # nothing watched


def test_watchdog_quiet_on_healthy_ticks():
    stalls = []
    with StallWatchdog(5.0, on_stall=stalls.append) as wd:
        for _ in range(3):
            with wd.watch("tick"):
                pass
    assert not stalls and not wd.events


def test_coordinator_tick_runs_under_armed_watchdog():
    """GridCoordinator.tick needs no plumbing: arming the process
    watchdog is enough for a wedged subscriber to be flagged, with the
    stall label naming the generation."""
    from gameoflifewithactors_tpu.coordinator import GridCoordinator

    coord = GridCoordinator((24, 32), "B3/S23", random_fill=0.3)
    stalls = []
    wd = watchdog_lib.arm(StallWatchdog(0.05, on_stall=stalls.append))
    try:
        unsub = coord.subscribe(lambda frame: time.sleep(0.4))
        coord.tick(1)
        unsub()
    finally:
        watchdog_lib.disarm()
    assert watchdog_lib.active_watchdog() is None
    assert len(stalls) == 1
    assert stalls[0].label.startswith("tick@gen")
    assert stalls[0].last_completed_span is not None
    assert wd.events == stalls


# -- RunReport ----------------------------------------------------------------


def test_run_report_json_round_trip(tmp_path):
    tr = SpanTracer()
    log = obs_compile.CompileEventLog()
    with tr.span("engine.step"):
        pass
    log.record(obs_compile.CompileEvent(
        runner="r", signature="uint32[8,8]", wall_seconds=1.25,
        cache_miss=True, donated=False, t0=0.0, t1=1.25))
    rep = report_lib.build_run_report(
        tracer=tr, compile_log=log,
        step_records=[{"generation": 8, "generations_stepped": 8,
                       "wall_seconds": 0.1, "cell_updates_per_sec": 1e6}],
        config={"side": 8}, halo_bytes={"model_per_gen": 4096,
                                        "measured_per_gen": None})
    path = rep.save(str(tmp_path / "report.json"))
    back = RunReport.load(path)
    assert back.to_dict() == rep.to_dict()
    assert back.schema_version == report_lib.SCHEMA_VERSION
    assert back.compile_seconds_total == pytest.approx(1.25)
    assert back.phase_seconds["engine.step"]["count"] == 1
    assert back.halo_bytes["model_per_gen"] == 4096
    # unknown keys from a future schema are ignored, not fatal
    d = rep.to_dict()
    d["from_the_future"] = True
    assert RunReport.from_dict(d).config == {"side": 8}
    # the human summary renders every section without raising
    text = "\n".join(back.summary_lines())
    assert "engine.step" in text and "compiles: 1" in text


def test_run_telemetry_session_end_to_end(tmp_path, cold_compile_cache):
    """begin_run_telemetry -> coordinator run -> finish: the report holds
    spans (dispatch/sync/readback separable), >= 1 compile event with
    wall seconds, StepMetrics, and halo-bytes figures — the ISSUE-1
    acceptance artifact, in-process. (cold_compile_cache: the cache_miss
    assertion below would flip to cache_hit under the suite's warm
    persistent cache once another run has compiled this shape.)"""
    from gameoflifewithactors_tpu.coordinator import GridCoordinator
    from gameoflifewithactors_tpu.scheduler import TickScheduler

    telem = begin_run_telemetry()
    # a fresh session must not inherit earlier runs' spans/compiles
    assert not spans_lib.TRACER.spans()
    assert not obs_compile.COMPILE_LOG.events()
    coord = GridCoordinator((40, 32), "B36/S23", random_fill=0.4,
                            track_population=True)
    telem.attach(coord)
    TickScheduler(coord, generations_per_tick=2).run(max_generations=6)
    rep = telem.finish(engine=coord.engine, config={"steps": 6})
    phases = rep.phase_seconds
    for name in ("scheduler.run", "coordinator.tick", "engine.step",
                 "engine.sync", "engine.snapshot"):
        assert name in phases, name
    assert phases["coordinator.tick"]["count"] == 3
    misses = [e for e in rep.compile_events if e["cache_miss"]]
    assert misses and all(e["wall_seconds"] > 0 for e in misses)
    assert len(rep.step_metrics) == 3
    assert rep.halo_bytes["model_per_gen"] == coord.engine.halo_bytes_per_gen(
        source="model")
    assert rep.config["steps"] == 6 and rep.config["rule"] == "B36/S23"
    assert rep.platform.get("platform") == "cpu"
    # saved artifact is the acceptance-criteria JSON
    back = RunReport.load(rep.save(str(tmp_path / "run.json")))
    assert back.to_dict() == rep.to_dict()


def test_report_cli_subcommand(tmp_path, capsys):
    from gameoflifewithactors_tpu import cli

    rep = report_lib.build_run_report(
        tracer=SpanTracer(), compile_log=obs_compile.CompileEventLog(),
        config={"demo": True})
    path = str(tmp_path / "r.json")
    rep.save(path)
    assert cli.main(["report", path]) == 0
    out = capsys.readouterr().out
    assert "RunReport" in out and "compiles: 0" in out
    assert cli.main(["report", path, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["config"] == {"demo": True}


# -- the StepMetrics compile-exclusion regression -----------------------------


def test_step_metrics_exclude_compile_time(cold_compile_cache):
    """ISSUE-1 regression: the compile a tick pays is reported in
    ``compile_seconds``, never inside ``wall_seconds`` — so post-warmup
    rates and first-tick rates describe the same quantity (stepping)."""
    from gameoflifewithactors_tpu.coordinator import GridCoordinator
    from gameoflifewithactors_tpu.utils.metrics import BufferSink, MetricsLogger

    buf = BufferSink()
    # an unusual (shape, rule) so this process has certainly not compiled
    # the runner yet: the first tick must pay and report the compile
    coord = GridCoordinator((72, 96), "B2/S345", random_fill=0.3,
                            metrics=MetricsLogger(buf))
    t0 = time.perf_counter()
    coord.tick(2)
    elapsed = time.perf_counter() - t0
    t1 = time.perf_counter()
    coord.tick(2)
    warm_elapsed = time.perf_counter() - t1

    first, warm = buf.records
    assert first.compile_seconds and first.compile_seconds > 0
    # wall = (step+sync time) - compile; elapsed >= step+sync, so the
    # bound below is exact arithmetic, not a timing guess
    assert first.wall_seconds <= elapsed - first.compile_seconds + 1e-6
    assert first.wall_seconds > 0
    # post-warmup: no compile to report, and the rate is computed from
    # a wall time in line with the actual tick duration
    assert warm.compile_seconds is None
    assert warm.wall_seconds <= warm_elapsed + 1e-6
    assert warm.cell_updates_per_sec == pytest.approx(
        72 * 96 * 2 / warm.wall_seconds)
    # serialized form drops the None, keeps the figure when present
    assert "compile_seconds" in first.to_dict()
    assert "compile_seconds" not in warm.to_dict()
