"""Evidence version-stamping (utils/provenance.py).

VERDICT.md round-2 Weak #1 / item #2: persisted TPU measurements must carry
the commit of the tree they measured, and consumers must flag records whose
measured code paths changed since. These tests run against a throwaway git
repo so they are independent of this repo's working-tree state.
"""

import subprocess

import pytest

from gameoflifewithactors_tpu.utils import provenance


def _git(repo, *args):
    return subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=repo, capture_output=True, text=True, check=True)


@pytest.fixture
def tmp_repo(tmp_path):
    _git(tmp_path, "init", "-q")
    hot = tmp_path / "gameoflifewithactors_tpu" / "ops"
    hot.mkdir(parents=True)
    (hot / "packed.py").write_text("v1\n")
    (hot / "bitpack.py").write_text("v1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "base")
    return tmp_path


def test_git_head_short_hash(tmp_repo):
    head = provenance.git_head(repo=str(tmp_repo))
    assert head and 6 <= len(head) <= 12


def test_no_commit_stamp_is_stale():
    assert provenance.staleness({"metric": "x (packed, soup, tpu)"})["stale"]


def test_fresh_when_paths_unchanged(tmp_repo):
    rec = {"metric": "cell-updates (packed, 50% soup, tpu)",
           "commit": provenance.git_head(repo=str(tmp_repo))}
    s = provenance.staleness(rec, repo=str(tmp_repo))
    assert not s["stale"], s


def test_stale_after_measured_path_commit(tmp_repo):
    rec = {"metric": "cell-updates (packed, 50% soup, tpu)",
           "commit": provenance.git_head(repo=str(tmp_repo))}
    (tmp_repo / "gameoflifewithactors_tpu" / "ops" / "packed.py").write_text("v2\n")
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-qm", "rewrite hot path")
    s = provenance.staleness(rec, repo=str(tmp_repo))
    assert s["stale"] and "packed.py" in s["reason"]


def test_stale_on_uncommitted_edit(tmp_repo):
    rec = {"metric": "cell-updates (packed, 50% soup, tpu)",
           "commit": provenance.git_head(repo=str(tmp_repo))}
    (tmp_repo / "gameoflifewithactors_tpu" / "ops" / "bitpack.py").write_text("dirty\n")
    s = provenance.staleness(rec, repo=str(tmp_repo))
    assert s["stale"] and "bitpack.py" in s["reason"]


def test_unrelated_change_stays_fresh(tmp_repo):
    rec = {"metric": "cell-updates (packed, 50% soup, tpu)",
           "commit": provenance.git_head(repo=str(tmp_repo))}
    (tmp_repo / "README.md").write_text("docs only\n")
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-qm", "docs")
    assert not provenance.staleness(rec, repo=str(tmp_repo))["stale"]


def test_head_stamp_marks_dirty_tree(tmp_repo):
    paths = ["gameoflifewithactors_tpu/ops"]
    clean = provenance.head_stamp(paths=paths, repo=str(tmp_repo))
    assert clean.get("commit") and "commit_dirty" not in clean
    (tmp_repo / "gameoflifewithactors_tpu" / "ops" / "packed.py").write_text("edit\n")
    dirty = provenance.head_stamp(paths=paths, repo=str(tmp_repo))
    assert dirty.get("commit_dirty") is True
    # a dirty-tree record can never be certified fresh
    rec = {"metric": "x (packed, soup, tpu)", **dirty}
    assert provenance.staleness(rec, repo=str(tmp_repo))["stale"]


def test_unparseable_backend_uses_conservative_paths(tmp_repo):
    # no "(backend, ...)" in the metric -> falls back to all-ops watch set
    rec = {"metric": "weird metric", "commit": provenance.git_head(repo=str(tmp_repo))}
    (tmp_repo / "gameoflifewithactors_tpu" / "ops" / "packed.py").write_text("v2\n")
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-qm", "hot change")
    assert provenance.staleness(rec, repo=str(tmp_repo))["stale"]


# --- round-5 precision (VERDICT r4 Weak #1/#3, item #2) ---------------------


def _commit_edit(repo, relpath, text, msg="edit"):
    p = repo / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(text)
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", msg)


def test_item_paths_ignore_unrelated_ops_edit(tmp_repo):
    # the VERDICT r4 "Done" shape: a CPU-side commit touching ops/sparse.py
    # must NOT stale the binary kernel's identity record (item= selects the
    # pallas set, which does not include sparse.py)
    rec = {"ok": True, "commit": provenance.git_head(repo=str(tmp_repo))}
    _commit_edit(tmp_repo, "gameoflifewithactors_tpu/ops/sparse.py", "v2\n",
                 "sparse feature work")
    s = provenance.staleness(rec, repo=str(tmp_repo), item="pallas_identity")
    assert not s["stale"], s
    # ...while an edit to a file the item DID measure still stales it
    _commit_edit(tmp_repo, "gameoflifewithactors_tpu/ops/packed.py", "v2\n",
                 "kernel rewrite")
    s = provenance.staleness(rec, repo=str(tmp_repo), item="pallas_identity")
    assert s["stale"] and "packed.py" in s["reason"]


def test_record_measured_paths_beats_item_and_metric(tmp_repo):
    # capture-time truth wins: the record names bitpack.py as its measured
    # set, so a packed.py edit (in both the metric set and any item set)
    # does not stale it
    rec = {"metric": "x (packed, soup, tpu)",
           "measured_paths": ["gameoflifewithactors_tpu/ops/bitpack.py"],
           "commit": provenance.git_head(repo=str(tmp_repo))}
    _commit_edit(tmp_repo, "gameoflifewithactors_tpu/ops/packed.py", "v2\n")
    assert not provenance.staleness(rec, repo=str(tmp_repo), item="bench_packed")["stale"]
    _commit_edit(tmp_repo, "gameoflifewithactors_tpu/ops/bitpack.py", "v2\n")
    assert provenance.staleness(rec, repo=str(tmp_repo))["stale"]


def test_comment_only_edit_stays_fresh(tmp_repo):
    hot = "gameoflifewithactors_tpu/ops/packed.py"
    _commit_edit(tmp_repo, hot, "x = 1\ny = x + 1\n", "real code")
    rec = {"metric": "x (packed, soup, tpu)",
           "commit": provenance.git_head(repo=str(tmp_repo))}
    # comment + blank-line edits: freeze notices must not destroy evidence
    _commit_edit(tmp_repo, hot,
                 "# FROZEN: serving record pallas_identity@93432f1\n\n"
                 "x = 1\ny = x + 1  # trailing note\n", "freeze notice")
    s = provenance.staleness(rec, repo=str(tmp_repo))
    assert not s["stale"], s
    assert "comment-only" in s["reason"]
    # but a real code change under the comments still stales
    _commit_edit(tmp_repo, hot, "# FROZEN\nx = 2\ny = x + 1\n", "real change")
    assert provenance.staleness(rec, repo=str(tmp_repo))["stale"]


def test_docstring_edit_is_code(tmp_repo):
    # docstrings are STRING tokens: editing one re-stales (conservative —
    # cited reference lines/claims live there)
    hot = "gameoflifewithactors_tpu/ops/packed.py"
    _commit_edit(tmp_repo, hot, '"""doc v1"""\nx = 1\n', "v1")
    rec = {"metric": "x (packed, soup, tpu)",
           "commit": provenance.git_head(repo=str(tmp_repo))}
    _commit_edit(tmp_repo, hot, '"""doc v2"""\nx = 1\n', "v2")
    assert provenance.staleness(rec, repo=str(tmp_repo))["stale"]


def test_head_stamp_embeds_measured_paths(tmp_repo):
    paths = ["gameoflifewithactors_tpu/ops/packed.py"]
    stamp = provenance.head_stamp(paths=paths, repo=str(tmp_repo))
    assert stamp["measured_paths"] == paths


def test_worklist_protocol_in_rate_items_not_assertion_items():
    # timing-protocol edits must stale rate records; pure-assertion records
    # (bit-identity, HLO structure) embed their cases and are exempt
    assert "scripts/tpu_worklist.py" in provenance.ITEM_PATHS["pallas_autotune"]
    assert "scripts/tpu_worklist.py" not in provenance.ITEM_PATHS["pallas_identity"]
    assert "scripts/tpu_worklist.py" not in provenance.ITEM_PATHS["ltl_lowering"]
    # every watcher item has a per-item set
    import re
    watch = open("scripts/tpu_watch.sh").read()
    items = re.search(r"^ITEMS=(\S+)", watch, re.M).group(1).split(",")
    assert set(items) <= set(provenance.ITEM_PATHS), \
        set(items) - set(provenance.ITEM_PATHS)


def test_explicit_record_paths_none_for_fallback():
    # the superset must never be embedded into a record (lock-in hazard)
    assert provenance.explicit_record_paths({"metric": "weird"}) is None
    assert provenance.record_paths({"metric": "weird"}) == provenance.ALL_OPS_PATHS
    stamp = provenance.head_stamp(paths=provenance.explicit_record_paths({}))
    assert "measured_paths" not in stamp


def test_head_stamp_comment_only_dirty_stays_clean(tmp_repo):
    hot = tmp_repo / "gameoflifewithactors_tpu" / "ops" / "packed.py"
    _commit_edit(tmp_repo, "gameoflifewithactors_tpu/ops/packed.py",
                 "x = 1\n", "code")
    paths = ["gameoflifewithactors_tpu/ops"]
    # an uncommitted freeze-notice comment must not brand captures dirty
    # (a permanently-stale record would re-burn TPU windows every watch)
    hot.write_text("# freeze notice\nx = 1\n")
    stamp = provenance.head_stamp(paths=paths, repo=str(tmp_repo))
    assert "commit_dirty" not in stamp, stamp
    # a real uncommitted code edit still does
    hot.write_text("x = 2\n")
    assert provenance.head_stamp(paths=paths, repo=str(tmp_repo)).get("commit_dirty")
    # ...and so does an untracked file in the measured paths
    hot.write_text("x = 1\n")
    (tmp_repo / "gameoflifewithactors_tpu" / "ops" / "new.py").write_text("y = 1\n")
    assert provenance.head_stamp(paths=paths, repo=str(tmp_repo)).get("commit_dirty")


def test_bench_protocol_edit_stales_bench_record(tmp_repo):
    # bench.py is part of every "(backend, ...)" record's measured set
    # (VERDICT r4 Weak #3): a timing-protocol edit flags the number
    _commit_edit(tmp_repo, "bench.py", "protocol = 1\n", "bench v1")
    rec = {"metric": "x (packed, soup, tpu)",
           "commit": provenance.git_head(repo=str(tmp_repo))}
    _commit_edit(tmp_repo, "bench.py", "protocol = 2\n", "bench v2")
    s = provenance.staleness(rec, repo=str(tmp_repo))
    assert s["stale"] and "bench.py" in s["reason"]


def test_protocol_scoped_staleness(tmp_repo):
    """Edits to a protocol file OUTSIDE its measurement functions don't
    stale (bench.py → run_bench; tpu_worklist.py → shared helpers + the
    record's own child function); edits INSIDE them do. This is what
    keeps a mid-window fix to one failing worklist child from re-staling
    every record captured minutes earlier in the same window."""
    bench_v1 = ("def run_bench(a):\n    return a + 1\n"
                "def report():\n    return 'v1'\n")
    _commit_edit(tmp_repo, "bench.py", bench_v1, "bench v1")
    rec = {"metric": "x (packed, soup, tpu)",
           "commit": provenance.git_head(repo=str(tmp_repo))}
    # reporting edit: record stays fresh, reason names the benign file
    _commit_edit(tmp_repo, "bench.py",
                 bench_v1.replace("'v1'", "'v2'"), "report change")
    s = provenance.staleness(rec, repo=str(tmp_repo))
    assert not s["stale"] and "protocol functions unchanged" in s["reason"]
    # measurement edit: stale
    _commit_edit(tmp_repo, "bench.py",
                 bench_v1.replace("a + 1", "a + 2"), "protocol change")
    assert provenance.staleness(rec, repo=str(tmp_repo))["stale"]


def test_worklist_scoping_needs_item_and_tracks_children(tmp_repo):
    wl_v1 = ("def _bench_rate(x):\n    return x\n"
             "def _sync_scalar(x):\n    return 1\n"
             "def _device_equal(a, b):\n    return a == b\n"
             "def child_pallas_band():\n    return 'band'\n"
             "def child_elementary():\n    return 'elem'\n")
    _commit_edit(tmp_repo, "scripts/tpu_worklist.py", wl_v1, "wl v1")
    head = provenance.git_head(repo=str(tmp_repo))
    rec = {"ok": True, "commit": head, "worklist_item": "pallas_band",
           "measured_paths": ["scripts/tpu_worklist.py"]}
    # another item's child changes: this record stays fresh (via its own
    # embedded worklist_item — no item= passed)
    _commit_edit(tmp_repo, "scripts/tpu_worklist.py",
                 wl_v1.replace("'elem'", "'elem2'"), "other child")
    s = provenance.staleness(rec, repo=str(tmp_repo))
    assert not s["stale"], s
    # same edit, but a record with NO known item: conservative full-file
    anon = {"ok": True, "commit": head,
            "measured_paths": ["scripts/tpu_worklist.py"]}
    assert provenance.staleness(anon, repo=str(tmp_repo))["stale"]
    # this record's own child changes: stale
    _commit_edit(tmp_repo, "scripts/tpu_worklist.py",
                 wl_v1.replace("'band'", "'band2'"), "own child")
    assert provenance.staleness(rec, repo=str(tmp_repo))["stale"]
    # a shared timing helper changes: stale for every item
    _commit_edit(tmp_repo, "scripts/tpu_worklist.py", wl_v1, "restore")
    rec2 = {**rec, "commit": provenance.git_head(repo=str(tmp_repo))}
    _commit_edit(tmp_repo, "scripts/tpu_worklist.py",
                 wl_v1.replace("return x", "return x * 2"), "helper")
    assert provenance.staleness(rec2, repo=str(tmp_repo))["stale"]


def test_protocol_scope_sees_decorator_changes(tmp_repo):
    # get_source_segment excludes decorators; a decorator swap on a
    # protocol function must still stale (it changes behavior)
    v1 = "def deco(f):\n    return f\n\n@deco\ndef run_bench(a):\n    return a\n"
    _commit_edit(tmp_repo, "bench.py", v1, "v1")
    rec = {"metric": "x (packed, soup, tpu)",
           "commit": provenance.git_head(repo=str(tmp_repo))}
    _commit_edit(tmp_repo, "bench.py",
                 v1.replace("@deco\ndef run_bench", "def run_bench"), "un-decorate")
    assert provenance.staleness(rec, repo=str(tmp_repo))["stale"]
