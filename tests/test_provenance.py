"""Evidence version-stamping (utils/provenance.py).

VERDICT.md round-2 Weak #1 / item #2: persisted TPU measurements must carry
the commit of the tree they measured, and consumers must flag records whose
measured code paths changed since. These tests run against a throwaway git
repo so they are independent of this repo's working-tree state.
"""

import subprocess

import pytest

from gameoflifewithactors_tpu.utils import provenance


def _git(repo, *args):
    return subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=repo, capture_output=True, text=True, check=True)


@pytest.fixture
def tmp_repo(tmp_path):
    _git(tmp_path, "init", "-q")
    hot = tmp_path / "gameoflifewithactors_tpu" / "ops"
    hot.mkdir(parents=True)
    (hot / "packed.py").write_text("v1\n")
    (hot / "bitpack.py").write_text("v1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "base")
    return tmp_path


def test_git_head_short_hash(tmp_repo):
    head = provenance.git_head(repo=str(tmp_repo))
    assert head and 6 <= len(head) <= 12


def test_no_commit_stamp_is_stale():
    assert provenance.staleness({"metric": "x (packed, soup, tpu)"})["stale"]


def test_fresh_when_paths_unchanged(tmp_repo):
    rec = {"metric": "cell-updates (packed, 50% soup, tpu)",
           "commit": provenance.git_head(repo=str(tmp_repo))}
    s = provenance.staleness(rec, repo=str(tmp_repo))
    assert not s["stale"], s


def test_stale_after_measured_path_commit(tmp_repo):
    rec = {"metric": "cell-updates (packed, 50% soup, tpu)",
           "commit": provenance.git_head(repo=str(tmp_repo))}
    (tmp_repo / "gameoflifewithactors_tpu" / "ops" / "packed.py").write_text("v2\n")
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-qm", "rewrite hot path")
    s = provenance.staleness(rec, repo=str(tmp_repo))
    assert s["stale"] and "packed.py" in s["reason"]


def test_stale_on_uncommitted_edit(tmp_repo):
    rec = {"metric": "cell-updates (packed, 50% soup, tpu)",
           "commit": provenance.git_head(repo=str(tmp_repo))}
    (tmp_repo / "gameoflifewithactors_tpu" / "ops" / "bitpack.py").write_text("dirty\n")
    s = provenance.staleness(rec, repo=str(tmp_repo))
    assert s["stale"] and "bitpack.py" in s["reason"]


def test_unrelated_change_stays_fresh(tmp_repo):
    rec = {"metric": "cell-updates (packed, 50% soup, tpu)",
           "commit": provenance.git_head(repo=str(tmp_repo))}
    (tmp_repo / "README.md").write_text("docs only\n")
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-qm", "docs")
    assert not provenance.staleness(rec, repo=str(tmp_repo))["stale"]


def test_head_stamp_marks_dirty_tree(tmp_repo):
    paths = ["gameoflifewithactors_tpu/ops"]
    clean = provenance.head_stamp(paths=paths, repo=str(tmp_repo))
    assert clean.get("commit") and "commit_dirty" not in clean
    (tmp_repo / "gameoflifewithactors_tpu" / "ops" / "packed.py").write_text("edit\n")
    dirty = provenance.head_stamp(paths=paths, repo=str(tmp_repo))
    assert dirty.get("commit_dirty") is True
    # a dirty-tree record can never be certified fresh
    rec = {"metric": "x (packed, soup, tpu)", **dirty}
    assert provenance.staleness(rec, repo=str(tmp_repo))["stale"]


def test_unparseable_backend_uses_conservative_paths(tmp_repo):
    # no "(backend, ...)" in the metric -> falls back to all-ops watch set
    rec = {"metric": "weird metric", "commit": provenance.git_head(repo=str(tmp_repo))}
    (tmp_repo / "gameoflifewithactors_tpu" / "ops" / "packed.py").write_text("v2\n")
    _git(tmp_repo, "add", "-A")
    _git(tmp_repo, "commit", "-qm", "hot change")
    assert provenance.staleness(rec, repo=str(tmp_repo))["stale"]
