"""The HLO contract gate (analysis/contracts.py + scripts/contract_check.py).

Three layers, cheapest first: registry enumeration (imports only), gate
semantics on fabricated results (pure functions — staleness, strict
mode, lost registrations), and the fails-closed pin — an injected extra
collective_permute must turn the gate red *naming the runner*. The
full-manifest strict run is tier-2 (slow): it lowers and compiles all
twelve runners.
"""

import json
import os
import subprocess
import sys

import pytest

from gameoflifewithactors_tpu.analysis import contracts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MANIFEST = os.path.join(REPO, contracts.MANIFEST_RELPATH)

GHOST = "sharded.multi_step_packed_ghost"


# -- registry -----------------------------------------------------------------


def test_registry_enumerates_every_runner_family():
    reg = contracts.load_registry()
    assert len(reg) >= 10, sorted(reg)
    for name, spec in reg.items():
        assert spec.name == name
        assert callable(spec.factory)
    # every subsystem with a runner shows up — ops, sharded, batched
    prefixes = {n.split(".")[0] for n in reg}
    assert {"ops", "sharded", "batched"} <= prefixes


def test_registry_refuses_duplicate_names():
    from gameoflifewithactors_tpu.ops._jit import register_builder

    with pytest.raises(ValueError, match=GHOST):
        register_builder(GHOST, lambda: None)


def test_check_all_rejects_unknown_only():
    with pytest.raises(KeyError, match="no_such_runner"):
        contracts.check_all(only=["no_such_runner"])


# -- manifest + gate semantics (pure, no lowering) ----------------------------


def _result(name="sharded.fake", *, count=4, nbytes=1536, errors=()):
    return contracts.RunnerContracts(
        name=name, tags=("sharded",), donated_argnums=(0,),
        donation_applied=True, host_transfer_sites=[],
        collective_permute_count=count, collective_permute_bytes=nbytes,
        expected_collective_bytes=None, collective_model="",
        errors=list(errors))


def _fresh_manifest(results):
    return contracts.build_manifest(results)


def test_gate_ok_against_fresh_manifest():
    r = _result()
    lines = contracts.gate([r], _fresh_manifest([r]), strict=True)
    assert lines == [f"ok {r.name} (count=4 bytes=1536)"]


def test_gate_fails_on_pinned_count_drift():
    r = _result(count=5)
    frozen = _fresh_manifest([_result(count=4)])
    lines = contracts.gate([r], frozen, strict=True)
    assert len(lines) == 1 and lines[0].startswith(f"FAIL {r.name}:")
    assert "count 5 != pinned 4" in lines[0]


def test_gate_stale_jax_skips_never_oks():
    r = _result()
    frozen = _fresh_manifest([r])
    frozen["jax"] = "0.0.0-elsewhere"
    lines = contracts.gate([r], frozen, strict=True)
    assert lines[0].startswith(f"skipped (stale) {r.name}")
    assert not any(l.startswith("ok ") for l in lines)


def test_gate_stale_jax_still_enforces_invariants():
    r = _result(errors=["sharded.fake: host transfer(s) in compiled HLO"])
    frozen = _fresh_manifest([_result()])
    frozen["jax"] = "0.0.0-elsewhere"
    lines = contracts.gate([r], frozen, strict=True)
    assert lines[0].startswith("FAIL sharded.fake:")


def test_gate_strict_fails_unpinned_runner():
    r = _result()
    lines = contracts.gate([r], _fresh_manifest([]), strict=True)
    assert lines[0].startswith(f"FAIL {r.name}: not pinned")
    loose = contracts.gate([r], _fresh_manifest([]), strict=False)
    assert loose[0].startswith(f"unpinned {r.name}")


def test_gate_fails_on_pinned_but_unregistered_runner():
    frozen = _fresh_manifest([_result("sharded.gone")])
    lines = contracts.gate([], frozen, strict=True)
    assert lines == ["FAIL sharded.gone: pinned in the manifest but no "
                     "longer registered — if the runner was removed on "
                     "purpose, regenerate the manifest with --write"]
    # --only runs check a subset: absence there is not a lost contract
    assert contracts.gate([], frozen, strict=True, complete=False) == []


def test_committed_manifest_pins_all_registered_runners():
    frozen = contracts.load_manifest(MANIFEST)
    assert frozen is not None, "results/hlo_contracts.json must be committed"
    reg = contracts.load_registry()
    assert set(frozen["runners"]) == set(reg)
    for name, entry in frozen["runners"].items():
        assert entry["host_transfer_sites"] == 0, name
        if entry["donated_argnums"]:
            assert entry["donation_applied"], name
    # the comm-avoiding runners pin their closed-form byte models
    deep = frozen["runners"]["sharded.multi_step_packed_deep"]
    ghost = frozen["runners"][GHOST]
    for entry in (deep, ghost):
        assert entry["expected_collective_bytes"] == \
            entry["collective_permute_bytes"]
        assert "exchange_bytes" in entry["collective_model"]


# -- fails-closed: the injection seam -----------------------------------------


def _run_contract_check(args, env_extra=None):
    env = dict(os.environ)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "contract_check.py"),
         *args],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)


def test_injected_collective_fails_the_gate_naming_the_runner():
    proc = _run_contract_check(["--only", GHOST],
                               env_extra={contracts.ENV_INJECT: GHOST})
    out = proc.stdout + proc.stderr
    assert proc.returncode == 1, out
    fails = [l for l in out.splitlines() if l.startswith("FAIL ")]
    assert len(fails) == 1, out  # donation survives: ONE contract trips
    assert fails[0].startswith(f"FAIL {GHOST}:")
    assert "collective-permute bytes" in fails[0]


@pytest.mark.slow
def test_strict_gate_green_against_committed_manifest(tmp_path):
    out_json = tmp_path / "contract_results.json"
    proc = _run_contract_check(["--strict", "--json", str(out_json)])
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out
    assert "0 failure(s)" in proc.stdout
    doc = json.loads(out_json.read_text())
    assert len(doc["results"]) >= 10
    assert all(not r["errors"] for r in doc["results"])
