"""Generations (multi-state) rule family: parser, stepper, engine, sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gameoflifewithactors_tpu import Engine
from gameoflifewithactors_tpu.models.generations import (
    BRIANS_BRAIN,
    STAR_WARS,
    GenRule,
    parse_any,
    parse_generations,
)
from gameoflifewithactors_tpu.models.rules import CONWAY, Rule
from gameoflifewithactors_tpu.ops.generations import (
    multi_step_generations,
    step_generations,
)
from gameoflifewithactors_tpu.ops.stencil import Topology


def oracle(g: np.ndarray, rule: GenRule, torus: bool, n: int) -> np.ndarray:
    """Plain-NumPy Generations reference."""
    g = g.astype(np.int32)
    for _ in range(n):
        alive = (g == 1).astype(np.int32)
        p = np.pad(alive, 1, mode="wrap") if torus else np.pad(alive, 1)
        cnt = sum(
            p[1 + dr : p.shape[0] - 1 + dr, 1 + dc : p.shape[1] - 1 + dc]
            for dr in (-1, 0, 1)
            for dc in (-1, 0, 1)
            if (dr, dc) != (0, 0)
        )
        born = (g == 0) & np.isin(cnt, sorted(rule.born))
        keep = (g == 1) & np.isin(cnt, sorted(rule.survive))
        g = np.where(born | keep, 1, np.where(g == 0, 0, (g + 1) % rule.states))
    return g.astype(np.uint8)


# -- parsing ------------------------------------------------------------------

def test_parse_notation_and_names():
    assert parse_generations("B2/S/C3") == BRIANS_BRAIN
    assert parse_generations("b2/s/g3") == BRIANS_BRAIN
    assert parse_generations("B2 / S / C3") == BRIANS_BRAIN
    assert parse_any("B2 / S / C3") == BRIANS_BRAIN
    assert parse_generations("brain") == BRIANS_BRAIN
    assert parse_generations("starwars") == STAR_WARS
    assert BRIANS_BRAIN.notation == "B2/S/C3"
    for bad in ("B2/S", "B2/S/C2", "B9/S/C3", "C3", "banana"):
        with pytest.raises(ValueError):
            parse_generations(bad)


def test_parse_any_dispatch():
    assert isinstance(parse_any("B3/S23"), Rule)
    assert parse_any("conway") == CONWAY
    assert isinstance(parse_any("B2/S/C3"), GenRule)
    assert parse_any(BRIANS_BRAIN) is BRIANS_BRAIN


# -- stepper vs oracle --------------------------------------------------------

@pytest.mark.parametrize("rule", [BRIANS_BRAIN, STAR_WARS,
                                  GenRule(frozenset({2, 3}), frozenset({2, 3}), 8)],
                         ids=str)
@pytest.mark.parametrize("topology", list(Topology), ids=lambda t: t.value)
def test_generations_matches_oracle(rule, topology):
    rng = np.random.default_rng(4)
    g = rng.integers(0, rule.states, size=(24, 40), dtype=np.uint8)
    want = oracle(g, rule, topology is Topology.TORUS, 8)
    got = np.asarray(multi_step_generations(
        jnp.asarray(g), 8, rule=rule, topology=topology))
    np.testing.assert_array_equal(got, want)


def test_dying_cells_do_not_excite():
    """A state-2 (dying) cell must not count as a neighbor: two dying cells
    beside a dead cell with no live neighbors birth nothing under B2."""
    g = np.zeros((5, 5), dtype=np.uint8)
    g[2, 1] = 2
    g[2, 3] = 2
    out = np.asarray(step_generations(jnp.asarray(g), rule=BRIANS_BRAIN))
    assert out[2, 2] == 0          # no birth from dying neighbors
    assert out[2, 1] == 0 and out[2, 3] == 0  # dying C3 cells die


def test_brians_brain_everything_dies_without_birth():
    """Brian's Brain has S = {}: every live cell starts dying immediately."""
    g = np.zeros((8, 8), dtype=np.uint8)
    g[3, 3] = 1
    out = np.asarray(step_generations(jnp.asarray(g), rule=BRIANS_BRAIN))
    assert out[3, 3] == 2


# -- engine / facade / checkpoint --------------------------------------------

def test_engine_generations_population_counts_alive_only():
    g = np.zeros((8, 32), dtype=np.uint8)
    g[2, 2] = 1
    g[2, 3] = 1
    g[5, 5] = 2  # dying: occupies space, not population
    e = Engine(g, "B2/S/C3")
    assert e.population() == 2
    e.step(1)
    np.testing.assert_array_equal(
        e.snapshot(), oracle(g, BRIANS_BRAIN, True, 1))


def test_engine_rejects_out_of_range_states_and_packed_kernels():
    g = np.full((4, 32), 3, dtype=np.uint8)
    with pytest.raises(ValueError, match="states 0..2"):
        Engine(g, "B2/S/C3")
    # pallas (single-device / row bands) and sparse (single-device and
    # sharded) are real Generations paths
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

    # 2D meshes flatten into row bands for the Generations kernel too
    e2d = Engine(np.zeros((16, 256), np.uint8), "B2/S/C3", backend="pallas",
                 mesh=mesh_lib.make_mesh((2, 4)))
    assert e2d.backend == "pallas" and e2d._banded
    e2d.step(2)
    assert e2d.population() == 0


def test_generations_checkpoint_roundtrip(tmp_path):
    from gameoflifewithactors_tpu.utils import checkpoint as ckpt

    rng = np.random.default_rng(6)
    g = rng.integers(0, 4, size=(16, 32), dtype=np.uint8)
    e = Engine(g, "starwars")
    e.step(5)
    p = ckpt.save(e, tmp_path / "gen.npz")
    e2 = ckpt.load_engine(p)
    assert e2.rule == STAR_WARS and e2.generation == 5
    np.testing.assert_array_equal(e2.snapshot(), e.snapshot())
    e.step(3)
    e2.step(3)
    np.testing.assert_array_equal(e2.snapshot(), e.snapshot())


def test_generations_sharded_bit_identity():
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.make_mesh((2, 4), jax.devices())
    rng = np.random.default_rng(7)
    g = rng.integers(0, 3, size=(32, 64), dtype=np.uint8)
    single = Engine(g, BRIANS_BRAIN)
    meshed = Engine(g, BRIANS_BRAIN, mesh=m)
    single.step(12)
    meshed.step(12)
    np.testing.assert_array_equal(meshed.snapshot(), single.snapshot())


def test_cli_generations_end_to_end(capsys):
    from gameoflifewithactors_tpu.cli import main as cli_main

    rc = cli_main(["--grid", "16x32", "--rule", "B2/S/C3", "--seed", "glider",
                   "--steps", "4", "--render", "final", "--population"])
    assert rc == 0
    assert "gen 4" in capsys.readouterr().out


def test_renderer_multistate_charset():
    import io

    from gameoflifewithactors_tpu.coordinator import RenderFrame
    from gameoflifewithactors_tpu.utils.render import ConsoleRenderer

    buf = io.StringIO()
    r = ConsoleRenderer(buf, ansi=False, charset=".#*")
    r(RenderFrame(grid=np.array([[0, 1, 2, 3]], dtype=np.uint8),
                  generation=1, population=None, full_shape=(1, 4)))
    assert buf.getvalue().splitlines()[0] == ".#**"  # state 3 reuses last glyph


def test_parse_any_surfaces_states_range_error():
    with pytest.raises(ValueError, match="3..256 states"):
        parse_any("B2/S/C300")
    with pytest.raises(ValueError, match="3..256 states"):
        parse_any("B2/S/C2")


def test_set_grid_validates_states():
    e = Engine(np.zeros((8, 32), np.uint8), "B2/S/C3")
    with pytest.raises(ValueError, match="states 0..2"):
        e.set_grid(np.full((8, 32), 7, np.uint8))


def test_gen_band_gens_per_exchange_needs_packing_width():
    """A requested exchange depth must not be silently dropped when the
    width can't pack into the bit-plane band runner (review contract)."""
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.make_mesh((2, 1), jax.devices()[:2])
    with pytest.raises(ValueError, match="does not pack"):
        Engine(np.zeros((16, 48), np.uint8), "B2/S/C3", backend="pallas",
               mesh=m, gens_per_exchange=8)
