"""Pallas temporal-blocked kernel vs the XLA SWAR path (interpret mode).

The kernel must be bit-identical to step_packed across topologies, rules,
block sizes, and temporal depths — including g spanning block boundaries
and DEAD-boundary exterior re-zeroing (the subtle one: exterior rows must
not evolve with the slab).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from gameoflifewithactors_tpu.models import seeds
from gameoflifewithactors_tpu.models.rules import CONWAY, DAY_AND_NIGHT, HIGHLIFE
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.packed import multi_step_packed
from gameoflifewithactors_tpu.ops.pallas_stencil import multi_step_pallas, step_rows
from gameoflifewithactors_tpu.ops.stencil import Topology


def _random_packed(h, w, seed=0):
    rng = np.random.default_rng(seed)
    return bitpack.pack(jnp.asarray(rng.integers(0, 2, size=(h, w), dtype=np.uint8)))


def test_step_rows_matches_packed_interior():
    """The slab primitive alone: interior rows of one generation."""
    want = multi_step_packed(_random_packed(24, 128), 1, rule=CONWAY, topology=Topology.TORUS)
    got = step_rows(_random_packed(24, 128), CONWAY, Topology.TORUS)  # rows 1..22
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want)[1:-1])


@pytest.mark.parametrize("topology", list(Topology), ids=lambda t: t.value)
@pytest.mark.parametrize("rule", [CONWAY, HIGHLIFE, DAY_AND_NIGHT], ids=str)
def test_pallas_bit_identity(rule, topology):
    p = _random_packed(64, 96, seed=7)
    want = multi_step_packed(p, 12, rule=rule, topology=topology)
    got = multi_step_pallas(
        _random_packed(64, 96, seed=7), 12,
        rule=rule, topology=topology,
        block_rows=16, gens_per_call=4, interpret=True,
    )
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack(got)), np.asarray(bitpack.unpack(want))
    )


@pytest.mark.parametrize("bh,g", [(8, 8), (16, 5), (32, 1), (64, 8)])
def test_pallas_block_and_depth_sweep(bh, g):
    """g == bh (max temporal depth), non-divisor g, single-block grids."""
    p = _random_packed(64, 64, seed=3)
    want = multi_step_packed(p, 11, rule=CONWAY, topology=Topology.TORUS)
    got = multi_step_pallas(
        _random_packed(64, 64, seed=3), 11,
        rule=CONWAY, topology=Topology.TORUS,
        block_rows=bh, gens_per_call=g, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pallas_dead_boundary_exterior_stays_dead():
    """Life hugging the global top/bottom edges with DEAD topology — the
    exterior-re-zero logic is what keeps edge blocks exact."""
    g = seeds.empty((32, 64))
    g[0, :20] = 1   # a line on the very first row
    g[-1, 30:50] = 1
    p = bitpack.pack(jnp.asarray(g))
    want = multi_step_packed(p, 10, rule=CONWAY, topology=Topology.DEAD)
    got = multi_step_pallas(
        bitpack.pack(jnp.asarray(g)), 10,
        rule=CONWAY, topology=Topology.DEAD,
        block_rows=8, gens_per_call=4, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_supported_gate():
    from gameoflifewithactors_tpu.ops.pallas_stencil import supported

    assert not supported((64, 2), on_tpu=True)     # 64-cell width: no native
    assert supported((64, 2), on_tpu=False)        # interpret: fine
    assert supported((16384, 512), on_tpu=True)    # 16384^2: native


def test_native_validation_rejects_bad_lane_and_vmem():
    # advisor round-2: an explicit native request with a misaligned width
    # or an over-budget block must fail with a clean ValueError here, not
    # an opaque Mosaic compile error on chip. interpret=False only builds
    # the validation path — the raise happens before any pallas_call.
    from gameoflifewithactors_tpu.models.rules import CONWAY
    from gameoflifewithactors_tpu.ops.pallas_stencil import (
        _VMEM_BUDGET,
        _vmem_bytes,
        band_supported,
        make_pallas_slab_step,
        make_pallas_step,
    )
    from gameoflifewithactors_tpu.ops.stencil import Topology

    # width 64 words: not lane-aligned (% 128) -> native rejects
    with pytest.raises(ValueError, match="128"):
        make_pallas_step(CONWAY, Topology.TORUS, (512, 64),
                         block_rows=64, interpret=False)
    with pytest.raises(ValueError, match="128"):
        make_pallas_slab_step(CONWAY, Topology.TORUS, (528, 64),
                              gens=8, block_rows=16, interpret=False)
    # explicit block so tall the double-buffered slab blows the VMEM budget
    wide = 128 * 40  # 163840-cell width, aligned
    bh = 4096
    assert _vmem_bytes(bh, 8, wide) > _VMEM_BUDGET
    with pytest.raises(ValueError, match="VMEM"):
        make_pallas_step(CONWAY, Topology.TORUS, (bh * 2, wide),
                         block_rows=bh, gens_per_call=8, interpret=False)
    # band gate mirrors the lane check instead of letting the mesh path
    # reach Mosaic with an unaligned width
    assert not band_supported(512, 8, native=True, wp=64)
    assert band_supported(512, 8, native=True, wp=128)


def test_runner_compile_cache_reused():
    from gameoflifewithactors_tpu.ops.pallas_stencil import _build_runner

    _build_runner.cache_clear()
    p = _random_packed(32, 64, seed=1)
    multi_step_pallas(p, 8, rule=CONWAY, topology=Topology.TORUS,
                      block_rows=16, gens_per_call=4, interpret=True)
    p2 = _random_packed(32, 64, seed=2)
    multi_step_pallas(p2, 8, rule=CONWAY, topology=Topology.TORUS,
                      block_rows=16, gens_per_call=4, interpret=True)
    info = _build_runner.cache_info()
    assert info.misses == 1 and info.hits >= 1


def test_pallas_glider_long_run():
    g = seeds.seeded((48, 96), "glider", 2, 2)
    p = bitpack.pack(jnp.asarray(g))
    got = multi_step_pallas(p, 48, rule=CONWAY, topology=Topology.TORUS,
                            block_rows=16, gens_per_call=6, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack(got)),
        np.roll(g, (12, 12), (0, 1)),
    )


class TestGenerationsKernel:
    """Temporal-blocked kernel over the Generations bit-plane stack."""

    @pytest.mark.parametrize("name", ["brain", "B2/S/C4"])
    @pytest.mark.parametrize("topology", list(Topology), ids=lambda t: t.value)
    @pytest.mark.parametrize("gens", [1, 8, 19])
    def test_bit_identity_vs_xla_planes(self, name, topology, gens):
        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.packed_generations import (
            multi_step_packed_generations,
            pack_generations_for,
        )
        from gameoflifewithactors_tpu.ops.pallas_stencil import (
            multi_step_pallas_generations,
        )

        rule = parse_any(name)
        rng = np.random.default_rng(9)
        grid = rng.integers(0, rule.states, size=(64, 64), dtype=np.uint8)
        planes = pack_generations_for(jnp.asarray(grid), rule)
        want = multi_step_packed_generations(planes, gens, rule=rule,
                                             topology=topology)
        got = multi_step_pallas_generations(
            jnp.array(planes), gens, rule=rule, topology=topology,
            interpret=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_engine_facade_and_fallback(self):
        from gameoflifewithactors_tpu import Engine

        rng = np.random.default_rng(1)
        g = rng.integers(0, 3, size=(64, 64), dtype=np.uint8)
        ref = Engine(g, "brain")                      # auto -> packed planes
        pal = Engine(g, "brain", backend="pallas")
        assert pal.backend == "pallas"
        ref.step(19)
        pal.step(19)
        np.testing.assert_array_equal(ref.snapshot(), pal.snapshot())
        assert pal.population() == ref.population()


class TestLtLKernel:
    """Radius-r LtL temporal-blocked kernel (interpret mode on the CPU
    rig; native identity/rate land via the ltl_pallas worklist item)."""

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    @pytest.mark.parametrize("gens", [1, 4, 11])
    def test_bit_identity_vs_bit_sliced(self, topology, gens):
        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_packed
        from gameoflifewithactors_tpu.ops.pallas_stencil import (
            multi_step_ltl_pallas,
        )

        rule = parse_any("bosco")
        rng = np.random.default_rng(41)
        p = jnp.asarray(rng.integers(0, 2 ** 32, size=(64, 4), dtype=np.uint32))
        want = multi_step_ltl_packed(p, gens, rule=rule, topology=topology)
        got = multi_step_ltl_pallas(p, gens, rule=rule, topology=topology,
                                    interpret=True, block_rows=16,
                                    gens_per_call=2)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_block_sweep_r2(self):
        from gameoflifewithactors_tpu.models.ltl import LtLRule
        from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_packed
        from gameoflifewithactors_tpu.ops.pallas_stencil import (
            multi_step_ltl_pallas,
        )

        rule = LtLRule(radius=2, born=(8, 12), survive=(9, 16))
        rng = np.random.default_rng(43)
        p = jnp.asarray(rng.integers(0, 2 ** 32, size=(96, 3), dtype=np.uint32))
        want = multi_step_ltl_packed(p, 12, rule=rule, topology=Topology.TORUS)
        for bh, g in ((12, 3), (24, 4), (48, 8)):
            got = multi_step_ltl_pallas(p, 12, rule=rule,
                                        topology=Topology.TORUS,
                                        interpret=True, block_rows=bh,
                                        gens_per_call=g)
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want),
                                          err_msg=f"bh={bh} g={g}")

    def test_gate_and_validation(self):
        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.pallas_stencil import (
            ltl_supported,
            make_ltl_pallas_step,
        )

        bosco = parse_any("bosco")
        diamond = parse_any("R2,C0,M0,S6..11,B6..9,NN")
        assert ltl_supported((16384, 512), bosco, on_tpu=True)
        assert ltl_supported((16384, 512), diamond, on_tpu=True)  # NN packs
        assert not ltl_supported((16384, 500), bosco, on_tpu=True)  # lane
        # r*g halo must be sublane-aligned natively: r=5, g=4 -> 20 % 8
        assert not ltl_supported((16384, 512), bosco, on_tpu=True,
                                 gens_per_call=4)
        with pytest.raises(ValueError, match="<= block_rows"):
            make_ltl_pallas_step(bosco, Topology.TORUS, (64, 4),
                                 block_rows=8, gens_per_call=2,
                                 interpret=True)

    @pytest.mark.parametrize("mesh_shape", [(4, 1), (2, 4)])
    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    def test_band_runner_bit_identity(self, mesh_shape, topology):
        import jax

        from gameoflifewithactors_tpu.models.ltl import LtLRule
        from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_packed
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
        from gameoflifewithactors_tpu.parallel import sharded

        rule = LtLRule(radius=2, born=(8, 12), survive=(9, 16))
        n = mesh_shape[0] * mesh_shape[1]
        m = mesh_lib.make_mesh(mesh_shape, jax.devices()[:n])
        rng = np.random.default_rng(53)
        p = jnp.asarray(rng.integers(0, 2 ** 32, size=(96, 4),
                                     dtype=np.uint32))
        want = multi_step_ltl_packed(p, 6, rule=rule, topology=topology)
        run = sharded.make_multi_step_ltl_pallas(
            m, rule, topology, gens_per_exchange=2, interpret=True)
        got = run(mesh_lib.device_put_sharded_grid(
            p, m, banded=mesh_shape[1] > 1), 3)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_engine_facade_band_mesh(self):
        import jax

        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        m = mesh_lib.make_mesh((4, 1), jax.devices()[:4])
        rng = np.random.default_rng(59)
        grid = rng.integers(0, 2, size=(96, 128), dtype=np.uint8)
        ref = Engine(grid, "R2,C0,M1,S9..16,B8..12", mesh=m,
                     backend="packed")
        got = Engine(grid, "R2,C0,M1,S9..16,B8..12", mesh=m,
                     backend="pallas", gens_per_exchange=2)
        ref.step(7)
        got.step(7)                      # 3 chunks + 1 remainder
        np.testing.assert_array_equal(ref.snapshot(), got.snapshot())
        # diamond rules ride the band kernel too (packed diamond sums)
        dref = Engine(grid, "R2,C0,M0,S6..11,B6..9,NN", mesh=m,
                      backend="packed")
        dgot = Engine(grid, "R2,C0,M0,S6..11,B6..9,NN", mesh=m,
                      backend="pallas", gens_per_exchange=2)
        dref.step(5)
        dgot.step(5)
        np.testing.assert_array_equal(dref.snapshot(), dgot.snapshot())
        # a width that cannot pack has no band kernel: an explicit
        # exchange depth must raise, not silently run dense per-generation
        # (review finding — mirrors the Generations contract)
        with pytest.raises(ValueError, match="needs the LtL band kernel"):
            Engine(np.zeros((96, 48), np.uint8), "bosco", mesh=m,
                   backend="pallas", gens_per_exchange=2)

    def test_band_guard_validates_band_dims_not_tile_dims(self):
        """(review finding) the constructor's LtL mesh guard must check
        BAND dimensions on the pallas path: a narrow full-width grid is
        fine (the width never shards over the mesh columns), while a band
        shorter than the radius must be rejected up front."""
        import jax

        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        m = mesh_lib.make_mesh((1, 8), jax.devices())
        grid = np.zeros((512, 32), np.uint8)   # 64-row bands, 1-word width
        e = Engine(grid, "R5,C0,M1,S34..58,B34..45", mesh=m,
                   backend="pallas", gens_per_exchange=8)
        e.step(8)                              # r*g = 40 <= 64-row bands
        assert e.population() == 0
        with pytest.raises(ValueError, match="smaller than the rule radius"):
            Engine(np.zeros((32, 32), np.uint8),   # 4-row bands < r = 5
                   "R5,C0,M1,S34..58,B34..45", mesh=m,
                   backend="pallas", gens_per_exchange=8)

    def test_engine_facade_and_fallback(self):
        import warnings as w

        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.ops.stencil import Topology as T

        rng = np.random.default_rng(47)
        grid = rng.integers(0, 2, size=(64, 128), dtype=np.uint8)
        ref = Engine(grid, "bosco", backend="packed", topology=T.DEAD)
        got = Engine(grid, "bosco", backend="pallas", topology=T.DEAD)
        ref.step(9)
        got.step(9)
        np.testing.assert_array_equal(ref.snapshot(), got.snapshot())
        # diamond rules ride the kernel now (per-row-separable sums):
        # bit-identity vs the dense path through the engine facade
        rng2 = np.random.default_rng(71)
        dgrid = rng2.integers(0, 2, size=(64, 128), dtype=np.uint8)
        dref = Engine(dgrid, "R2,C0,M0,S6..11,B6..9,NN", backend="dense")
        dgot = Engine(dgrid, "R2,C0,M0,S6..11,B6..9,NN", backend="pallas")
        dref.step(6)
        dgot.step(6)
        np.testing.assert_array_equal(dref.snapshot(), dgot.snapshot())
        # a grid shorter than the r*g halo has no block decomposition even
        # in interpret mode: the gate must say so and the engine fall back
        # to the bit-sliced path instead of crashing in step()
        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            short = Engine(np.zeros((32, 32), np.uint8), "bosco",
                           backend="pallas", topology=T.DEAD)
        assert any("falling back" in str(c.message) for c in caught)
        short.step(2)                     # must run on the fallback path
        assert short.population() == 0


def test_ltl_vmem_gate_calibration_and_guards(monkeypatch):
    """The r5 scoped-VMEM rework (first native LtL compile, worklist
    ltl_pallas @700b444: Mosaic measured 17.74 MB scoped against its
    16 MiB default cap at bosco g=8, bh=512, Wp=256): the radius-scaled
    model, the device-gated cap, and the explicit-block guard."""
    import jax

    from gameoflifewithactors_tpu.models.generations import parse_any
    from gameoflifewithactors_tpu.ops import pallas_stencil as ps

    # calibration point (r=5 box -> 26.96 count planes measured beside
    # the 2 revolving buffers; Mosaic's "17.74M" is binary MiB =
    # 18,601,738 bytes) and the conservative linear extrapolation over
    # window rows, floored
    assert ps._ltl_vmem_planes(5) == 27
    assert ps._ltl_vmem_planes(7) == 37
    assert ps._ltl_vmem_planes(1) == 10
    planes = [ps._ltl_vmem_planes(r) for r in range(1, 8)]
    assert planes == sorted(planes)
    # the model at the measured failure point must cover the measurement
    assert ps._ltl_vmem_bytes(512, 40, 256, r=5) >= 18_601_738

    # the previously failing bench shape stays supported for the v4+
    # target (non-TPU hosts predict for it; conftest forces CPU here)
    bosco = parse_any("bosco")
    assert ps.ltl_supported((1024, 256), bosco, on_tpu=True)
    assert ps._ltl_vmem_budget() == ps._LTL_VMEM_BUDGET

    # device gating of the scoped cap by generation digit
    class _Dev:
        def __init__(self, kind, platform="tpu"):
            self.device_kind = kind
            self.platform = platform

    for kind, want_raised in (("TPU v3", False), ("TPU v2", False),
                              ("TPU v4", True), ("TPU v5 lite", True),
                              ("TPU7x", True), ("weird-core", False)):
        monkeypatch.setattr(jax, "devices", lambda k=kind: [_Dev(k)])
        got = ps._ltl_vmem_limit()
        assert bool(got) == want_raised, kind
        # the block-picking budget must match the cap the compile will
        # request, so ltl_supported never admits what Mosaic rejects
        assert ps._ltl_vmem_budget() == (
            ps._LTL_VMEM_BUDGET if want_raised else ps._VMEM_BUDGET), kind
    monkeypatch.undo()

    # explicit block_rows bypasses _pick_bh: the footprint guard must
    # raise a clean ValueError, not hand Mosaic an over-cap allocation
    with pytest.raises(ValueError, match="VMEM footprint"):
        ps.make_ltl_pallas_step(bosco, Topology.TORUS, (8192, 4096),
                                block_rows=8192, gens_per_call=8,
                                interpret=False)


def test_validate_slab_threads_caller_budget():
    """Advisor r5 #1: the LtL slab caller validates against its own
    model/budget through _validate_slab, so an over-budget LtL shape is
    rejected with the LtL figures — never the misleading binary '14 MiB
    budget' message — and a shape inside the LtL budget is never falsely
    rejected by the binary check."""
    from gameoflifewithactors_tpu.models.generations import parse_any
    from gameoflifewithactors_tpu.ops import pallas_stencil as ps

    bosco = parse_any("bosco")  # r=5 box
    hr = bosco.radius * 8
    # oversized explicit block: the raised LtL budget must appear in the
    # error, proving the threaded budget (not _VMEM_BUDGET) was used
    with pytest.raises(ValueError) as exc:
        ps.make_ltl_pallas_slab_step(
            bosco, Topology.TORUS, (8192 + 2 * hr, 4096), gens=8,
            block_rows=8192 + 2 * hr, interpret=False)
    assert f"{ps._ltl_vmem_budget() >> 20} MiB" in str(exc.value)

    # a shape inside the LtL budget passes the threaded check natively
    # (raising _LTL_VMEM_BUDGET past 49 MiB used to flip such shapes to
    # a false binary-budget rejection; now the binary default is not
    # consulted for this caller at all)
    bh, Wp = 296, 256
    He = 2 * bh  # = band + 2*hr with hr = 40
    ltl_model = ps._ltl_vmem_model(bosco.radius)
    assert ltl_model(bh, hr, Wp) <= ps._LTL_VMEM_BUDGET
    ps._validate_slab(He, bh, hr, False, Wp=Wp,
                      vmem_bytes=ltl_model, budget=ps._LTL_VMEM_BUDGET)


def test_binary_model_within_budget_whenever_ltl_model_is():
    """Advisor r5 #1, the coincidence pinned: for every shape the LtL
    model admits under _LTL_VMEM_BUDGET, the binary model stays under
    _VMEM_BUDGET (binary <= 2/7 * ltl and 2/7 * 48 MiB < 14 MiB). Any
    budget/model change breaking this must consciously revisit every
    _validate_slab caller still using the binary default."""
    from gameoflifewithactors_tpu.ops import pallas_stencil as ps

    for r in range(1, 8):
        ltl = ps._ltl_vmem_model(r)
        for bh in (8, 64, 512, 2048):
            for g in (8, 16, 40, 56):
                hr = r * g
                for Wp in (128, 256, 1024, 4096):
                    if ltl(bh, hr, Wp) <= ps._LTL_VMEM_BUDGET:
                        assert ps._vmem_bytes(bh, hr, Wp) <= ps._VMEM_BUDGET, (
                            r, bh, hr, Wp)


def test_tpu_generation_env_override(monkeypatch):
    """Advisor r5 #3: GOLTPU_TPU_GENERATION names the *target* core, so
    AOT cross-lowering for a pre-v4 chip can opt into the conservative
    cap/budget from any host (the host-platform fallback would lift it)."""
    from gameoflifewithactors_tpu.ops import pallas_stencil as ps

    # conftest forces CPU: the host fallback answers for the v4+ target
    assert ps._ltl_vmem_limit() == ps._LTL_VMEM_LIMIT
    for target, want_limit, want_budget in (
            ("3", 0, ps._VMEM_BUDGET),
            ("v2", 0, ps._VMEM_BUDGET),
            ("v5e", ps._LTL_VMEM_LIMIT, ps._LTL_VMEM_BUDGET),
            ("tpu7x", ps._LTL_VMEM_LIMIT, ps._LTL_VMEM_BUDGET)):
        monkeypatch.setenv("GOLTPU_TPU_GENERATION", target)
        assert ps._ltl_vmem_limit() == want_limit, target
        # the budget keys off the same decision, so block picking and
        # the requested cap can never disagree under the override either
        assert ps._ltl_vmem_budget() == want_budget, target
    monkeypatch.setenv("GOLTPU_TPU_GENERATION", "latest")
    with pytest.raises(ValueError, match="GOLTPU_TPU_GENERATION"):
        ps._ltl_vmem_limit()


def test_pre_v4_model_safety_factor(monkeypatch):
    """ADVICE r5 #2: the count-plane term of the LtL VMEM model is
    calibrated from ONE Mosaic measurement (r=5 box, g=8, bh=512,
    Wp=256); on pre-v4 cores the 14-vs-16 MiB budget gap absorbs only
    ~2 MiB of extrapolation error, so the model is inflated by
    _LTL_MODEL_SAFETY_PRE_V4 there — and ONLY there (v4+ keeps the
    uninflated model: its 48-vs-64 MiB slack already exceeds the
    factor)."""
    from gameoflifewithactors_tpu.ops import pallas_stencil as ps

    r, bh, g, Wp = 3, 256, 8, 128
    hr = r * g
    base = ps._ltl_vmem_bytes(bh, hr, Wp, r=r)
    monkeypatch.setenv("GOLTPU_TPU_GENERATION", "v5e")
    assert ps._ltl_vmem_model(r)(bh, hr, Wp) == base
    monkeypatch.setenv("GOLTPU_TPU_GENERATION", "3")
    inflated = ps._ltl_vmem_model(r)(bh, hr, Wp)
    assert inflated == int(base * ps._LTL_MODEL_SAFETY_PRE_V4) > base
    # the factor actually bites: a shape the raw model would admit at
    # the pre-v4 budget is rejected once inflated (block picking then
    # chooses a shorter block instead of flying 2 MiB from the ceiling)
    budget = ps._VMEM_BUDGET
    bh_edge = next(b for b in range(1024, 8, -8)
                   if ps._ltl_vmem_bytes(b, hr, 256, r=r) <= budget
                   and int(ps._ltl_vmem_bytes(b, hr, 256, r=r)
                           * ps._LTL_MODEL_SAFETY_PRE_V4) > budget)
    assert ps._ltl_vmem_model(r)(bh_edge, hr, 256) > budget
