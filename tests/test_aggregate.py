"""Fleet aggregation (obs/aggregate.py): merged metrics that never lie,
merged timelines that never reorder.

The claims under test (README "Fleet observability"):

- **no silent summing** — ``merge_expositions`` tags every series with
  its ``proc`` label and preserves it; ``sum_across_procs`` REFUSES
  per-chip gauges (the COST check: a summed per-chip rate is a fleet
  number no chip produced);
- **clock alignment** — two subprocess tapes whose ``perf_counter``
  origins differ by minutes merge into one monotonic epoch timeline via
  each process's wall↔perf anchor; tapes without an anchor land under
  ``unaligned``, never at a fabricated time;
- **provenance** — flight-dump trigger headers survive the merge
  verbatim; a live scrape of N workers yields one exposition with one
  row per worker, and a down worker is an absent row, not a zero.
"""

import json
import urllib.request

import pytest

from gameoflifewithactors_tpu.obs.aggregate import (
    AggregatorServer,
    FleetAggregator,
    PerChipSumError,
    base_name,
    merge_expositions,
    merge_flight_dumps,
    merge_timelines,
    parse_exposition,
    series_across_procs,
    sum_across_procs,
    validate_timeline,
    write_merged_timeline,
)
from gameoflifewithactors_tpu.obs.exporter import render_prometheus, serve_metrics
from gameoflifewithactors_tpu.obs.registry import MetricsRegistry

# -- exposition merge ---------------------------------------------------------


def _exposition(**series) -> str:
    reg = MetricsRegistry()
    for name, value in series.items():
        if name.endswith("_total"):
            reg.counter(name, "a counter").inc(value)
        else:
            reg.gauge(name, "a gauge").set(value)
    return render_prometheus(reg.snapshot())


def test_merge_expositions_labels_every_series_with_proc():
    merged = merge_expositions({
        "w0": _exposition(session_steps_total=5, tenant_steps_per_sec=10.5),
        "w1": _exposition(session_steps_total=7, tenant_steps_per_sec=3.25),
    })
    parsed = parse_exposition(merged)
    procs = {labels["proc"] for _n, labels, _v in parsed["samples"]}
    assert procs == {"w0", "w1"}
    rows = series_across_procs({"w0": merged}, "tenant_steps_per_sec")
    assert sorted(v for _p, _l, v in rows) == [3.25, 10.5]


def test_merge_expositions_preserves_histogram_families():
    reg = MetricsRegistry()
    h = reg.histogram("session_phase_seconds", "phases")
    h.observe(0.01, phase="admission", tenant="t0")
    h.observe(0.5, phase="dispatch", tenant="t0")
    merged = merge_expositions(
        {"w0": render_prometheus(reg.snapshot())})
    parsed = parse_exposition(merged)
    names = {n for n, _l, _v in parsed["samples"]}
    fam = "goltpu_session_phase_seconds"
    assert {f"{fam}_bucket", f"{fam}_sum", f"{fam}_count"} <= names
    # cumulative le buckets survive per proc, still a valid histogram
    assert parsed["types"][fam] == "histogram"
    counts = [(labels, v) for n, labels, v in parsed["samples"]
              if n == f"{fam}_count"]
    assert all(labels["proc"] == "w0" for labels, _v in counts)


def test_merge_expositions_refuses_preexisting_proc_label():
    merged = merge_expositions({"w0": _exposition(session_steps_total=1)})
    with pytest.raises(ValueError, match="proc label"):
        merge_expositions({"again": merged})


def test_sum_across_procs_refuses_per_chip_gauges():
    per_proc = {
        "w0": _exposition(session_steps_total=5, tenant_steps_per_sec=10.0,
                          hbm_bytes_in_use=2 ** 20),
        "w1": _exposition(session_steps_total=7, tenant_steps_per_sec=3.0,
                          hbm_bytes_in_use=2 ** 21),
    }
    # additive counters sum fine
    assert sum_across_procs(per_proc, "session_steps_total") == 12.0
    # per-chip gauges refuse: the honest view is the per-proc series
    for name in ("tenant_steps_per_sec", "hbm_bytes_in_use"):
        with pytest.raises(PerChipSumError, match="per-chip"):
            sum_across_procs(per_proc, name)
    assert len(series_across_procs(per_proc, "tenant_steps_per_sec")) == 2


def test_base_name_strips_prefix_and_histogram_suffixes():
    assert base_name("goltpu_session_phase_seconds_bucket") == \
        "session_phase_seconds"
    assert base_name("goltpu_sessions_live") == "sessions_live"
    assert base_name("plain_count") == "plain"


# -- timeline merge -----------------------------------------------------------


def _write_dump(path, *, anchor, spans=(), events=(), reason="test",
                trace_id=None, pid=1234):
    """A fabricated flight dump: the exact JSONL shape
    FlightRecorder.dump writes (tests/test_obs.py pins that shape)."""
    header = {"type": "flight", "schema_version": 1, "reason": reason,
              "pid": pid, "epoch_anchor": anchor, "trace_id": trace_id}
    if anchor is None:
        del header["epoch_anchor"]
    lines = [json.dumps(header)]
    lines += [json.dumps({"type": "span", **s}) for s in spans]
    lines += [json.dumps({"type": "event", **e}) for e in events]
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def test_skewed_clocks_merge_monotonic(tmp_path):
    # process A booted long ago: large perf_counter, small anchor;
    # process B booted just now: tiny perf_counter, larger anchor. On
    # raw perf_counter, B's span would sort before A's — wall order is
    # the reverse.
    a = _write_dump(tmp_path / "a.jsonl", anchor=1000.0, pid=1,
                    spans=[{"name": "a.late", "t0": 100.0, "t1": 101.0,
                            "thread": "main"}])
    b = _write_dump(tmp_path / "b.jsonl", anchor=1090.0, pid=2,
                    spans=[{"name": "b.early", "t0": 5.0, "t1": 6.0,
                            "thread": "main"}],
                    events=[{"kind": "kill", "t": 5.5, "thread": "main"}])
    merged = merge_flight_dumps([a, b])
    timed = [e for e in merged["traceEvents"] if e["ph"] != "M"]
    assert [e["name"] for e in timed] == ["b.early", "kill", "a.late"]
    assert validate_timeline(merged) == []
    # wall = perf + anchor, microseconds
    assert timed[0]["ts"] == pytest.approx((5.0 + 1090.0) * 1e6)
    assert timed[-1]["ts"] == pytest.approx((100.0 + 1000.0) * 1e6)


def test_merge_preserves_trigger_headers_verbatim(tmp_path):
    a = _write_dump(tmp_path / "a.jsonl", anchor=10.0, pid=1,
                    reason="peer lost (heartbeat): [2]",
                    trace_id="ab" * 16)
    merged = merge_flight_dumps([a])
    hdr = merged["flight_headers"]["a"]
    assert hdr["reason"] == "peer lost (heartbeat): [2]"
    assert hdr["trace_id"] == "ab" * 16
    assert hdr["pid"] == 1


def test_anchorless_dump_lands_in_unaligned_not_misplaced(tmp_path):
    old = _write_dump(tmp_path / "old.jsonl", anchor=None, pid=9,
                      spans=[{"name": "old.span", "t0": 1.0, "t1": 2.0,
                              "thread": "main"}])
    new = _write_dump(tmp_path / "new.jsonl", anchor=50.0, pid=2,
                      spans=[{"name": "new.span", "t0": 1.0, "t1": 2.0,
                              "thread": "main"}])
    merged = merge_flight_dumps([old, new])
    assert merged["unaligned"] == ["old"]
    names = [e["name"] for e in merged["traceEvents"] if e["ph"] != "M"]
    assert names == ["new.span"]  # nothing placed at a fabricated time
    assert "old" in merged["flight_headers"]  # provenance still kept


def test_validate_timeline_flags_negative_and_out_of_order():
    bad = {"traceEvents": [
        {"ph": "X", "name": "n1", "ts": 100.0, "dur": -5.0},
        {"ph": "X", "name": "n2", "ts": 50.0, "dur": 1.0},
    ]}
    problems = validate_timeline(bad)
    assert any("negative duration" in p for p in problems)
    assert any("out-of-order" in p for p in problems)


def test_write_merged_timeline_roundtrip(tmp_path):
    a = _write_dump(tmp_path / "w0.jsonl", anchor=5.0, pid=1,
                    spans=[{"name": "s", "t0": 1.0, "t1": 2.0,
                            "thread": "main", "trace_id": "cd" * 16,
                            "span_id": "11" * 8, "parent_id": "22" * 8}])
    out = write_merged_timeline(str(tmp_path / "timeline.json"),
                                flight_dumps=[a])
    merged = json.loads((tmp_path / "timeline.json").read_text())
    assert out.endswith("timeline.json")
    span = [e for e in merged["traceEvents"] if e["ph"] == "X"][0]
    # trace ids ride along into the chrome-trace args
    assert span["args"]["trace_id"] == "cd" * 16
    assert span["args"]["parent_id"] == "22" * 8
    assert validate_timeline(merged) == []


def test_merge_timelines_unions_extras():
    t1 = {"traceEvents": [{"ph": "X", "name": "a", "ts": 2.0, "dur": 1.0}],
          "flight_headers": {"w0": {"reason": "r0"}}}
    t2 = {"traceEvents": [{"ph": "M", "pid": 1, "tid": 0,
                           "name": "process_name", "args": {"name": "d"}},
                          {"ph": "X", "name": "b", "ts": 1.0, "dur": 1.0}],
          "unaligned": ["legacy"]}
    merged = merge_timelines([t1, t2])
    timed = [e["name"] for e in merged["traceEvents"] if e["ph"] != "M"]
    assert timed == ["b", "a"]  # re-sorted across sources
    assert merged["traceEvents"][0]["ph"] == "M"  # meta stays first
    assert merged["flight_headers"] == {"w0": {"reason": "r0"}}
    assert merged["unaligned"] == ["legacy"]


# -- live scraping ------------------------------------------------------------


def test_fleet_aggregator_scrapes_labels_and_tolerates_down(tmp_path):
    regs = {name: MetricsRegistry() for name in ("w0", "w1")}
    regs["w0"].counter("session_steps_total", "steps").inc(3)
    regs["w1"].counter("session_steps_total", "steps").inc(4)
    servers = {name: serve_metrics(0, registry=reg)
               for name, reg in regs.items()}
    try:
        targets = {name: f"127.0.0.1:{srv.port}"
                   for name, srv in servers.items()}
        targets["w2"] = "127.0.0.1:1"  # nothing listens there
        agg = FleetAggregator(targets, ttl_seconds=0.0)
        assert agg.up() == {"w0": True, "w1": True, "w2": False}
        merged = agg.render()
        parsed = parse_exposition(merged)
        rows = [(labels["proc"], v) for n, labels, v in parsed["samples"]
                if n == "goltpu_session_steps_total"]
        # one row per live worker; the down one is absent, not zero
        assert sorted(rows) == [("w0", 3.0), ("w1", 4.0)]
        with AggregatorServer(agg, 0) as front:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{front.port}/metrics",
                    timeout=5) as r:
                assert 'proc="w1"' in r.read().decode()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{front.port}/fleet", timeout=5) as r:
                assert json.loads(r.read())["up"]["w2"] is False
    finally:
        for srv in servers.values():
            srv.stop()


def test_fleet_aggregator_ttl_cache_coalesces(tmp_path):
    calls = []

    class Probe(FleetAggregator):
        def _fetch(self, url):
            calls.append(url)
            return "goltpu_x_total 1\n"

    agg = Probe({"w0": "127.0.0.1:9"}, ttl_seconds=60.0)
    agg.scrape()
    agg.scrape()  # served from cache: no second fetch
    assert len(calls) == 1
    agg.scrape(force=True)
    assert len(calls) == 2
