"""Donation is opt-in: functional entry points must not consume arguments.

Round-1 TPU runs surfaced that always-donating jits (ops/_jit.py has the
story) killed any caller that reused its input — invisible on the CPU
backend, fatal on TPU. These tests pin the contract: by default the input
array survives and can be re-passed (want/got harness pattern); with
``donate=True`` the call still computes the same result (the donated
variant is a distinct jit instance, so both code paths need exercising).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from gameoflifewithactors_tpu.models.generations import parse_any
from gameoflifewithactors_tpu.models.ltl import parse_ltl
from gameoflifewithactors_tpu.models.rules import CONWAY
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.generations import multi_step_generations
from gameoflifewithactors_tpu.ops.ltl import multi_step_ltl
from gameoflifewithactors_tpu.ops.packed import multi_step_packed, step_packed
from gameoflifewithactors_tpu.ops.pallas_stencil import multi_step_pallas
from gameoflifewithactors_tpu.ops.stencil import Topology, multi_step


def _soup(shape, hi=2, dtype=np.uint8, seed=11):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, hi, size=shape, dtype=dtype))


CASES = [
    ("dense", lambda p, n, **kw: multi_step(p, n, rule=CONWAY, **kw),
     lambda: _soup((48, 48))),
    ("packed", lambda p, n, **kw: multi_step_packed(p, n, rule=CONWAY, **kw),
     lambda: _soup((48, 2), hi=2 ** 32, dtype=np.uint32)),
    # gens_per_call=4 < n so the pallas *loop* runs (chunks=1) and the
    # remainder path too — both donation flags are exercised
    ("pallas", lambda p, n, **kw: multi_step_pallas(
        p, n, rule=CONWAY, interpret=True, gens_per_call=4, **kw),
     lambda: _soup((48, 2), hi=2 ** 32, dtype=np.uint32)),
    ("generations", lambda p, n, **kw: multi_step_generations(
        p, n, rule=parse_any("brain"), **kw),
     lambda: _soup((48, 48), hi=3)),
    ("ltl", lambda p, n, **kw: multi_step_ltl(p, n, rule=parse_ltl("bosco"), **kw),
     lambda: _soup((48, 48))),
]


@pytest.mark.parametrize("name,run,mk", CASES, ids=[c[0] for c in CASES])
def test_input_survives_by_default(name, run, mk):
    p = mk()
    first = run(p, 5)
    # the caller's array must still be usable: re-run from the same input
    assert not p.is_deleted()
    again = run(p, 5)
    np.testing.assert_array_equal(np.asarray(first), np.asarray(again))


@pytest.mark.parametrize("name,run,mk", CASES, ids=[c[0] for c in CASES])
def test_donating_variant_matches(name, run, mk):
    p = mk()
    want = np.asarray(run(p, 5))
    got = np.asarray(run(mk(), 5, donate=True))
    np.testing.assert_array_equal(want, got)


def test_default_variant_lowered_without_aliasing():
    # The real pin for the round-1 TPU bug: donation is a no-op on the CPU
    # backend these tests run on, so is_deleted()/reuse checks above cannot
    # fail if someone reverts to always-donating jits. The lowered MLIR can:
    # donated args carry tf.aliasing_output, on every backend.
    p = _soup((32, 2), hi=2 ** 32, dtype=np.uint32)
    plain = multi_step_packed.jitted.lower(p, 3, rule=CONWAY).as_text()
    donating = multi_step_packed.jitted_donating.lower(p, 3, rule=CONWAY).as_text()
    assert "tf.aliasing_output" not in plain
    assert "tf.aliasing_output" in donating


def test_step_packed_donation_contract():
    p = _soup((32, 2), hi=2 ** 32, dtype=np.uint32)
    a = step_packed(p, rule=CONWAY, topology=Topology.DEAD)
    assert not p.is_deleted()
    b = step_packed(p, rule=CONWAY, topology=Topology.DEAD)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    bitpack.unpack(a)  # outputs stay live either way


# -- the PR 11 use-after-free, pinned as a lint fixture -----------------------
#
# The runtime tests above can't catch the original bug on CPU (donation
# is a no-op there). GOL008 catches it at review time instead; these
# fixtures pin that the committed buggy shape is flagged and that the
# shipped fix — jnp.array(x, copy=True) — comes back clean.

import textwrap

from gameoflifewithactors_tpu.analysis.lint import lint_source

_PR11_BUG = textwrap.dedent("""
    import jax.numpy as jnp

    from gameoflifewithactors_tpu.parallel import sharded


    def soak(mesh, caller_grid, gens):
        run = sharded.make_multi_step_packed(mesh, "conway", donate=True)
        p = jnp.asarray(caller_grid)
        out = run(p, gens)
        return out, caller_grid.sum()
""")

_PR11_FIX = _PR11_BUG.replace("jnp.asarray(caller_grid)",
                              "jnp.array(caller_grid, copy=True)")


def test_gol008_flags_the_pr11_donated_alias():
    findings = [f for f in lint_source(_PR11_BUG, "examples/soak.py").findings
                if f.code == "GOL008"]
    assert findings, "the PR 11 alias-into-donated-call shape must flag"
    assert any("caller_grid" in f.message and "use-after-free" in f.message
               for f in findings)


def test_gol008_clean_on_the_shipped_copy_fix():
    rep = lint_source(_PR11_FIX, "examples/soak.py")
    assert [f for f in rep.findings if f.code == "GOL008"] == []


def test_gol008_flags_read_after_donation_without_rebind():
    src = textwrap.dedent("""
        from gameoflifewithactors_tpu.parallel import sharded


        def drive(mesh, p, gens):
            run = sharded.make_multi_step_packed(mesh, "conway", donate=True)
            out = run(p, gens)
            return out, p.sum()
    """)
    msgs = [f.message for f in lint_source(src, "examples/drive.py").findings
            if f.code == "GOL008"]
    assert any("read after being donated" in m for m in msgs), msgs


def test_gol008_clean_on_rebind_after_donate():
    src = textwrap.dedent("""
        from gameoflifewithactors_tpu.parallel import sharded


        def drive(mesh, p, gens):
            run = sharded.make_multi_step_packed(mesh, "conway", donate=True)
            for _ in range(gens):
                p = run(p, 1)
            return p
    """)
    rep = lint_source(src, "examples/drive.py")
    assert [f for f in rep.findings if f.code == "GOL008"] == []
