"""Bit-packed SWAR path: pack/unpack round-trip + bit-identity vs dense."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gameoflifewithactors_tpu.models import seeds
from gameoflifewithactors_tpu.models.rules import CONWAY, DAY_AND_NIGHT, HIGHLIFE, SEEDS
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.packed import multi_step_packed, step_packed
from gameoflifewithactors_tpu.ops.stencil import Topology, step


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    g = rng.integers(0, 2, size=(17, 96), dtype=np.uint8)
    p = bitpack.pack(jnp.asarray(g))
    assert p.shape == (17, 3) and p.dtype == jnp.uint32
    np.testing.assert_array_equal(np.asarray(bitpack.unpack(p)), g)


def test_pack_rejects_ragged_width():
    with pytest.raises(ValueError):
        bitpack.pack(jnp.zeros((4, 33), dtype=jnp.uint8))


@pytest.mark.parametrize("shape", [(1, 32), (10, 64), (37, 96), (64, 1024)])
def test_pack_np_matches_pack(shape):
    """bench.py builds every initial state via the host-side pack; it must
    honor the exact bit-i-of-word-j layout contract of the device pack."""
    rng = np.random.default_rng(7)
    g = rng.integers(0, 2, size=shape, dtype=np.uint8)
    host = bitpack.pack_np(g)
    assert host.dtype == np.uint32
    np.testing.assert_array_equal(host, np.asarray(bitpack.pack(jnp.asarray(g))))
    np.testing.assert_array_equal(np.asarray(bitpack.unpack(jnp.asarray(host))), g)


def test_pack_np_noncontiguous_input():
    g = np.zeros((8, 128), dtype=np.uint8)
    g[:, ::3] = 1
    view = g[::2, 32:96]  # strided, offset view
    np.testing.assert_array_equal(
        bitpack.pack_np(view), np.asarray(bitpack.pack(jnp.asarray(np.ascontiguousarray(view)))))


def test_population_exact():
    rng = np.random.default_rng(3)
    g = rng.integers(0, 2, size=(64, 128), dtype=np.uint8)
    assert bitpack.population(bitpack.pack(jnp.asarray(g))) == int(g.sum())


@pytest.mark.parametrize("rule", [CONWAY, HIGHLIFE, DAY_AND_NIGHT, SEEDS], ids=str)
@pytest.mark.parametrize("topology", list(Topology), ids=lambda t: t.value)
def test_packed_matches_dense(rule, topology):
    """Word-boundary and grid-boundary bits are where SWAR bugs live, so use
    a width spanning several words and odd heights."""
    rng = np.random.default_rng(11)
    g = rng.integers(0, 2, size=(37, 160), dtype=np.uint8)
    dense = jnp.asarray(g)
    packed = bitpack.pack(jnp.asarray(g))
    for _ in range(4):
        dense = step(dense, rule=rule, topology=topology)
        packed = step_packed(packed, rule=rule, topology=topology)
    np.testing.assert_array_equal(np.asarray(bitpack.unpack(packed)), np.asarray(dense))


def test_packed_glider_golden():
    g = seeds.seeded((32, 64), "glider", 2, 2)
    p = bitpack.pack(jnp.asarray(g))
    out = multi_step_packed(p, 4, rule=CONWAY)
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack(out)),
        np.roll(g, (1, 1), (0, 1)),
    )


def test_packed_word_boundary_crossing():
    """A glider crossing column 32 (a word boundary) must stay intact."""
    g = seeds.seeded((16, 96), "glider", 4, 28)
    dense = jnp.asarray(g)
    p = bitpack.pack(jnp.asarray(g))
    for _ in range(12):  # glider moves 3 cells right, crossing col 32
        dense = step(dense, rule=CONWAY)
        p = step_packed(p, rule=CONWAY)
    np.testing.assert_array_equal(np.asarray(bitpack.unpack(p)), np.asarray(dense))
    assert np.asarray(bitpack.unpack(p)).sum() == 5


@pytest.mark.parametrize("topology", list(Topology), ids=lambda t: t.value)
def test_row_sum_bits_match_reference_planes(topology):
    """The production row-sum count path must agree bit-for-bit with the
    reference 8-plane CSA formulation on random grids (both are kept: the
    reference is the spec, the row-sum form is the fast path)."""
    from gameoflifewithactors_tpu.ops.packed import (
        _step_whole,
        apply_rule_planes,
        bit_sliced_sum,
        neighbor_planes,
    )

    rng = np.random.default_rng(41)
    for _ in range(4):
        p = jnp.asarray(rng.integers(0, 2 ** 32, size=(16, 8), dtype=np.uint32))
        want = apply_rule_planes(
            p, bit_sliced_sum(neighbor_planes(p, topology)), CONWAY)
        got = _step_whole(p, CONWAY, topology)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_count_bits_ext_matches_reference_planes():
    """Same spec-vs-fast-path cross-check for the halo-extended tile form
    (count_bits_ext vs the 8-plane neighbor_planes_ext reference)."""
    from gameoflifewithactors_tpu.ops.packed import (
        apply_rule_planes,
        bit_sliced_sum,
        count_bits_ext,
        neighbor_planes_ext,
        step_packed_ext,
    )

    rng = np.random.default_rng(43)
    for _ in range(4):
        ext = jnp.asarray(rng.integers(0, 2 ** 32, size=(18, 10), dtype=np.uint32))
        center, planes = neighbor_planes_ext(ext)
        want = apply_rule_planes(center, bit_sliced_sum(planes), CONWAY)
        np.testing.assert_array_equal(
            np.asarray(step_packed_ext(ext, CONWAY)), np.asarray(want))
        alive, bits = count_bits_ext(ext)
        np.testing.assert_array_equal(np.asarray(alive), np.asarray(center))
