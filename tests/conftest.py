"""Test harness config: force an 8-fake-device CPU JAX platform.

Must run before any jax import (SURVEY.md §5 — the sharding-equivalence
tests stand in for multi-chip hardware, the standard JAX idiom). Bench and
production paths never import this; they see the real TPU.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
