"""Test harness config: force an 8-fake-device CPU JAX platform.

The sharding-equivalence tests stand in for multi-chip hardware
(SURVEY.md §5), the standard JAX idiom. Note the tunneled TPU plugin in
this image ignores the JAX_PLATFORMS *env var*, so we must also set the
``jax_platforms`` config before the first backend query. Bench and
production paths never import this; they see the real TPU.
"""

import os
import sys

# Drop the tunneled-TPU PJRT plugin from the import path entirely: when the
# tunnel is wedged (observed repeatedly), plugin discovery hangs `import jax`
# itself, even under JAX_PLATFORMS=cpu. Tests are CPU-only by design.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import axon_guard  # noqa: E402  (repo-root helper; must not import jax)

axon_guard.strip_import_path()

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (env must be staged first)

jax.config.update("jax_platforms", "cpu")
