"""Test harness config: force an 8-fake-device CPU JAX platform.

The sharding-equivalence tests stand in for multi-chip hardware
(SURVEY.md §5), the standard JAX idiom. Note the tunneled TPU plugin in
this image ignores the JAX_PLATFORMS *env var*, so we must also set the
``jax_platforms`` config before the first backend query. Bench and
production paths never import this; they see the real TPU.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (env must be staged first)

jax.config.update("jax_platforms", "cpu")
