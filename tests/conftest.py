"""Test harness config: force an 8-fake-device CPU JAX platform.

The sharding-equivalence tests stand in for multi-chip hardware
(SURVEY.md §5), the standard JAX idiom. Note the tunneled TPU plugin in
this image ignores the JAX_PLATFORMS *env var*, so we must also set the
``jax_platforms`` config before the first backend query. Bench and
production paths never import this; they see the real TPU.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
import axon_guard  # noqa: E402  (repo-root helper; must not import jax)

# Warm start for the suite itself (aot/, ISSUE 2): the tier-1 run used
# to recompile the same runners in every process — pin the persistent
# compile cache to a repo-local dir (gitignored) so repeat runs skip
# every previously-seen XLA compile. Deliberately NOT the user-level
# ~/.cache default: a user-populated AOT registry there could swap
# engine runners (no buffer donation on the AOT path) under tests that
# assert donation/compile behavior, and tests must not depend on — or
# pollute — machine-global state. CI overrides via GOLTPU_CACHE_DIR to
# the dir its actions/cache step carries across runs. Tests that assert
# COLD-compile behavior pin their own dir via the cold_compile_cache
# fixture below; everything else is cache-state-agnostic.
if os.environ.get("GOLTPU_CACHE_DIR") is None:
    os.environ["GOLTPU_CACHE_DIR"] = os.path.join(_REPO, ".goltpu_cache")

axon_guard.force_cpu(8)


import pytest  # noqa: E402


@pytest.fixture
def cold_compile_cache(tmp_path, monkeypatch):
    """A guaranteed-cold warm-start cache for tests that assert
    real-compile behavior (first-tick compile events, compile_seconds >
    0): a warm session cache — CI deliberately carries one across runs —
    would turn their compiles into cache_hit events and flip them."""
    from gameoflifewithactors_tpu.aot import cache as aot_cache
    import jax

    cold = tmp_path / "warmstart"
    monkeypatch.setenv(aot_cache.ENV_CACHE_DIR, str(cold))
    saved_state = dict(aot_cache._state)
    saved_dir = jax.config.jax_compilation_cache_dir
    aot_cache.ensure_persistent_cache(str(cold))
    yield str(cold)
    aot_cache._state.update(saved_state)
    jax.config.update("jax_compilation_cache_dir", saved_dir)


# Runtime sanitizers (analysis/sanitizers.py, GOLTPU_SANITIZE=1): run the
# dense-engine step tests under jax's device→host transfer guard, so a
# future edit that slips an implicit readback into the step loop fails
# tier-1 loudly instead of silently serializing TPU pipelines. The
# engine's sanctioned readbacks (snapshot/population/active_tiles, the
# sparse step scalar) carry their own allow-scopes — the guard only bites
# on *undeclared* syncs. Scoped to the dense-engine module: its tests
# drive every step/observe surface, and test helpers elsewhere do their
# own ad-hoc host fetches by design.
_TRANSFER_GUARDED_MODULES = ("tests.test_engine_dense",)


@pytest.fixture(autouse=True)
def _sanitize_transfer_guard(request):
    from gameoflifewithactors_tpu.analysis import sanitizers

    module = getattr(request, "module", None)
    if sanitizers.enabled() and \
            getattr(module, "__name__", "") in _TRANSFER_GUARDED_MODULES:
        with sanitizers.no_implicit_host_transfers():
            yield
    else:
        yield


def pytest_configure(config):
    # the ROADMAP tier-1 command deselects these (-m 'not slow'); register
    # the mark so its use never degrades into an unknown-mark warning
    config.addinivalue_line(
        "markers", "slow: excluded from the CPU tier-1 verify run "
        "(pathological XLA CPU compile time or TPU-scale shapes)")
