"""Test harness config: force an 8-fake-device CPU JAX platform.

The sharding-equivalence tests stand in for multi-chip hardware
(SURVEY.md §5), the standard JAX idiom. Note the tunneled TPU plugin in
this image ignores the JAX_PLATFORMS *env var*, so we must also set the
``jax_platforms`` config before the first backend query. Bench and
production paths never import this; they see the real TPU.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import axon_guard  # noqa: E402  (repo-root helper; must not import jax)

axon_guard.force_cpu(8)


def pytest_configure(config):
    # the ROADMAP tier-1 command deselects these (-m 'not slow'); register
    # the mark so its use never degrades into an unknown-mark warning
    config.addinivalue_line(
        "markers", "slow: excluded from the CPU tier-1 verify run "
        "(pathological XLA CPU compile time or TPU-scale shapes)")
