"""Multi-process distributed backend, end to end (SURVEY.md §6 'Distributed
communication backend').

Spawns N real OS processes; jax.distributed forms the multi-controller
system over localhost, the (2, N) global mesh spans both processes' devices,
and the sharded torus step's ppermute halos cross the process boundary.
Every process must independently report bit-identity with the single-device
engine. This is the strongest no-real-cluster evidence the image allows —
actual cross-process collectives, not fake devices in one process.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("n_procs", [2, 3])
def test_cross_process_halo_exchange_bit_identity(n_procs):
    port = _free_port()
    env = {**os.environ}
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), str(n_procs), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(n_procs)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"proc {i} failed:\n{err[-2500:]}"
        assert f"MULTIHOST-OK proc={i}/{n_procs}" in out
