"""Serving layer (serve/): thousands of sessions on a handful of lanes.

The claims under test, per the serving contract (README "Serving"):

- **bit-identity** — a session multiplexed onto shared masked lanes,
  through divergent step cursors, ladder compaction, checkpoint/resume,
  and lane-crash recovery, always equals a dedicated oracle run of its
  own seed (tests/oracle.py);
- **zero post-warm retraces** — the capacity ladder is a closed set, so
  create/close/compaction churn never compiles (retrace_budget(0));
- **admission** — a faked exhausted device (the DeviceSampler backend
  seam) provably rejects/queues creates, and frees drain the queue;
- **observability** — per-tenant ``goltpu_session_steps_total`` and the
  queue-depth gauge reach the exposition, /healthz carries live counts.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from gameoflifewithactors_tpu.analysis.sanitizers import retrace_budget
from gameoflifewithactors_tpu.models.generations import parse_any
from gameoflifewithactors_tpu.obs.device import DeviceSampler
from gameoflifewithactors_tpu.obs.exporter import render_prometheus
from gameoflifewithactors_tpu.obs.registry import MetricsRegistry
from gameoflifewithactors_tpu.ops.stencil import Topology
from gameoflifewithactors_tpu.resilience.supervisor import RestartPolicy
from gameoflifewithactors_tpu.serve import (
    AdmissionController,
    AdmissionRejected,
    LanePool,
    SessionService,
    SpecFamily,
    decode_words,
    encode_words,
)
from gameoflifewithactors_tpu.serve.frontend import SessionFrontend
from gameoflifewithactors_tpu.serve.session import Session

from .oracle import numpy_run

SPEC = {"rule": "B3/S23", "height": 16, "width": 32, "topology": "torus"}
FAMILIES = (
    {"rule": "B3/S23", "height": 32, "width": 32, "topology": "torus"},
    {"rule": "B36/S23", "height": 32, "width": 32, "topology": "torus"},
    {"rule": "B3/S23", "height": 16, "width": 32, "topology": "dead"},
)
FILL = 0.35


def expected_grid(spec: dict, rng_seed: int, gens: int,
                  fill: float = FILL) -> np.ndarray:
    """The dedicated-engine oracle: same seeding contract as
    SessionService._seed_words, evolved by the NumPy reference."""
    h, w = spec["height"], spec["width"]
    seed = (np.random.default_rng(rng_seed).random((h, w))
            < fill).astype(np.uint8)
    return numpy_run(seed, parse_any(spec["rule"]),
                     Topology(spec.get("topology", "torus")), gens)


def make_service(ladder=(1, 2, 4), *, admission_kw=None, **kw):
    reg = MetricsRegistry()
    adm = AdmissionController(registry=reg, **(admission_kw or {}))
    return SessionService(ladder=ladder, registry=reg, admission=adm,
                          sleep_fn=lambda s: None, **kw), reg


# -- units --------------------------------------------------------------------


def test_session_lifecycle_enforced():
    s = Session(sid="s1", tenant="t", family_key="k", spec=dict(SPEC))
    assert s.state == "pending" and not s.live
    s.transition("packed")
    s.transition("running")
    assert s.live
    s.transition("closed")
    with pytest.raises(ValueError, match="illegal transition"):
        s.transition("running")


def test_spec_family_rejects_non_lane_specs():
    with pytest.raises(ValueError, match="binary life-like"):
        SpecFamily("brain", 32, 32)  # Generations rule: not a lane family
    with pytest.raises(ValueError, match="mesh"):
        SpecFamily.from_spec(dict(SPEC, mesh="auto"))
    with pytest.raises(ValueError):
        SpecFamily("B3/S23", 32, 33)  # width % 32
    # 'auto' resolves to the packed lane runner; shape spelling accepted
    fam = SpecFamily.from_spec({"rule": "B3/S23", "shape": [16, 32],
                                "backend": "auto"})
    assert fam.backend == "packed" and fam.slot_bytes() == 2 * 16 * 1 * 4


def test_lane_ladder_plan():
    pool = LanePool(SpecFamily.from_spec(SPEC), ladder=(1, 2, 4))
    assert pool.plan(0) == []
    assert pool.plan(1) == [1]
    assert pool.plan(3) == [4]
    assert pool.plan(5) == [4, 1]
    assert pool.plan(11) == [4, 4, 4]


def test_wire_codec_roundtrip():
    words = np.random.default_rng(5).integers(
        0, 2 ** 32, size=(16, 1), dtype=np.uint32)
    assert np.array_equal(decode_words(encode_words(words), 16, 1), words)
    with pytest.raises(ValueError, match="expected"):
        decode_words("abcd", 16, 1)


# -- bit-identity under multiplexing ------------------------------------------


def test_property_create_close_step_matches_oracle():
    """Random create/close/step interleavings: every surviving session is
    bit-identical to a dedicated engine of its own seed — packing,
    divergent cursors, and compaction are semantically invisible."""
    svc, _ = make_service(ladder=(1, 2, 4))
    rng = np.random.default_rng(1234)
    live = {}  # sid -> (spec_idx as rng_seed, gens)
    next_seed = 0
    for _ in range(60):
        op = rng.choice(["create", "step", "close"],
                        p=[0.4, 0.45, 0.15] if live else [1, 0, 0])
        if op == "create":
            info = svc.create("prop", SPEC, fill=FILL, rng_seed=next_seed)
            live[info["sid"]] = [next_seed, 0]
            next_seed += 1
        elif op == "step":
            sid = rng.choice(sorted(live))
            n = int(rng.integers(1, 4))
            svc.step(sid, n)
            live[sid][1] += n
        else:
            sid = rng.choice(sorted(live))
            svc.close(sid)
            del live[sid]
    assert live, "the op mix must leave survivors to verify"
    for sid, (seed, gens) in live.items():
        np.testing.assert_array_equal(
            svc.grid(sid), expected_grid(SPEC, seed, gens),
            err_msg=f"{sid} diverged after {gens} gens")


def test_divergent_cursors_on_one_lane():
    """Sessions sharing a lane step different amounts per call; the
    min-positive-debt pump with the occupancy mask must keep each
    trajectory exact (including a zero-step idler)."""
    svc, _ = make_service(ladder=(4,))
    sids = [svc.create("t", SPEC, fill=FILL, rng_seed=i)["sid"]
            for i in range(4)]
    plan = [5, 2, 7, 0]
    for sid, n in zip(sids, plan):
        if n:
            svc.step(sid, n)
    for sid, n in zip(sids, plan):
        np.testing.assert_array_equal(
            svc.grid(sid), expected_grid(SPEC, sids.index(sid), n))


# -- retrace discipline -------------------------------------------------------


def test_ladder_churn_zero_postwarm_retraces():
    """After warm(), arbitrary create/step/close churn — including the
    growth and compaction repacks that move sessions across ladder
    shapes — must not pay a single real XLA compile."""
    svc, _ = make_service(ladder=(1, 2, 4))
    svc.warm(SPEC)
    with retrace_budget(0, context="serve ladder churn"):
        sids = [svc.create("t", SPEC, fill=FILL, rng_seed=i)["sid"]
                for i in range(7)]  # grows 1 -> 2 -> 4 -> 4+4
        for sid in sids:
            svc.step(sid, 2)
        for sid in sids[::2]:  # compaction back down the ladder
            svc.close(sid)
        for sid in sids[1::2]:
            svc.step(sid, 3)
    pool = svc.pools[SpecFamily.from_spec(SPEC).key]
    assert pool.compactions > 0, "the churn must actually repack"


# -- admission ----------------------------------------------------------------


def fake_device(bytes_in_use: int, bytes_limit: int):
    return [{"device": "0", "platform": "tpu",
             "bytes_in_use": bytes_in_use, "peak_bytes_in_use": bytes_in_use,
             "bytes_limit": bytes_limit}]


def test_admission_rejects_on_fake_hbm_exhaustion():
    """The acceptance scenario: hbm gauges (fed through the real
    DeviceSampler backend seam) report an exhausted device; with no
    queue room the create is refused outright, and the decision lands on
    the exposition."""
    svc, reg = make_service(admission_kw={"queue_limit": 0})
    DeviceSampler(registry=reg,
                  backend=lambda: fake_device(2 ** 30 - 10, 2 ** 30)
                  ).sample_once()
    with pytest.raises(AdmissionRejected, match="over HBM budget"):
        svc.create("t", SPEC, fill=FILL)
    text = render_prometheus(reg.snapshot())
    assert ('goltpu_session_admission_total{decision="reject",tenant="t"} 1'
            in text)
    assert svc.counts()["sessions"]["total"] == 0


def test_admission_queue_then_drain_on_free():
    svc, reg = make_service(admission_kw={"queue_limit": 4})
    state = {"in_use": 2 ** 30 - 10}
    sampler = DeviceSampler(
        registry=reg,
        backend=lambda: fake_device(state["in_use"], 2 ** 30))
    sampler.sample_once()
    info = svc.create("t", SPEC, fill=FILL, rng_seed=3)
    assert info["state"] == "pending"
    assert svc.admission.queue_depth() == 1
    # debt accrues while parked; applies after admission
    svc.step(info["sid"], 4)
    assert svc.info(info["sid"])["state"] == "pending"
    state["in_use"] = 0  # closes elsewhere freed the memory
    sampler.sample_once()
    svc.pump()  # drains the queue into the freed budget
    svc.pump()  # applies the parked debt
    assert svc.admission.queue_depth() == 0
    got = svc.info(info["sid"])
    assert got["state"] == "running" and got["generation"] == 4
    np.testing.assert_array_equal(
        svc.grid(info["sid"]), expected_grid(SPEC, 3, 4))
    text = render_prometheus(reg.snapshot())
    # queue waits land in the custom bucket boundaries, not the
    # step-latency decades
    assert 'goltpu_session_queue_wait_seconds_bucket' in text
    assert 'le="300"' in text
    assert 'goltpu_session_queue_depth 0' in text


def test_admission_queue_overflow_rejects():
    svc, reg = make_service(admission_kw={"queue_limit": 1})
    DeviceSampler(registry=reg,
                  backend=lambda: fake_device(2 ** 30, 2 ** 30)).sample_once()
    assert svc.create("t", SPEC, fill=FILL)["state"] == "pending"
    with pytest.raises(AdmissionRejected):
        svc.create("t", SPEC, fill=FILL)


def test_admission_permissive_without_limit_gauge():
    """CPU host-RSS publishes no hbm_bytes_limit: a gauge that does not
    exist must admit, not refuse, traffic."""
    svc, _ = make_service()
    assert svc.create("t", SPEC, fill=FILL)["state"] == "packed"


# -- lane recovery ------------------------------------------------------------


def test_lane_crash_recovery_bit_identical():
    """An injected lane fault mid-debt: the lane restores from recovery
    snapshots, lost generations replay as re-credited debt, and the
    final grids equal the never-faulted oracle."""
    svc, reg = make_service(ladder=(4,))
    sids = [svc.create("t", SPEC, fill=FILL, rng_seed=i)["sid"]
            for i in range(3)]
    for sid in sids:
        svc.step(sid, 3)
    s0 = svc.store.get(sids[0])
    lane = svc.pools[s0.family_key].lanes[s0.lane_id]
    lane.fail_next = True
    svc.step(sids[0], 5)  # the pump hits the fault, recovers, replays
    for i, (sid, gens) in enumerate(zip(sids, (8, 3, 3))):
        assert svc.info(sid)["generation"] == gens
        np.testing.assert_array_equal(
            svc.grid(sid), expected_grid(SPEC, i, gens),
            err_msg=f"session {i} not bit-identical after lane recovery")
    assert reg.counter("session_lane_recoveries_total").value(
        family=s0.family_key) == 1


def test_lane_circuit_open_evicts_not_wedges():
    svc, reg = make_service(
        ladder=(2,), policy=RestartPolicy(max_restarts=2,
                                          backoff_initial_seconds=0.0))
    sid = svc.create("t", SPEC, fill=FILL)["sid"]
    s = svc.store.get(sid)
    lane = svc.pools[s.family_key].lanes[s.lane_id]

    def always_fails(n, mask):
        raise RuntimeError("wedged lane")

    lane.step = always_fails
    svc.step(sid, 1)  # restarts burn the budget, then the circuit opens
    assert svc.info(sid)["state"] == "evicted"
    assert reg.counter("session_evictions_total").value(
        family=s.family_key) == 1
    # the service is not wedged: fresh creates land on a fresh lane
    sid2 = svc.create("t", SPEC, fill=FILL, rng_seed=9)["sid"]
    svc.step(sid2, 2)
    np.testing.assert_array_equal(svc.grid(sid2),
                                  expected_grid(SPEC, 9, 2))
    with pytest.raises(ValueError, match="evicted"):
        svc.step(sid, 1)


# -- checkpoint / resume ------------------------------------------------------


def test_checkpoint_resume_roundtrip(tmp_path):
    ck = str(tmp_path / "sessions.npz")
    svc, reg = make_service(checkpoint_path=ck,
                            admission_kw={"queue_limit": 4})
    sids = [svc.create("t", SPEC, fill=FILL, rng_seed=i)["sid"]
            for i in range(3)]
    for i, sid in enumerate(sids):
        svc.step(sid, 2 + i)
    # park one more behind a faked full device, with debt outstanding
    DeviceSampler(registry=reg,
                  backend=lambda: fake_device(2 ** 30, 2 ** 30)).sample_once()
    queued = svc.create("t", SPEC, fill=FILL, rng_seed=99)["sid"]
    svc.step(queued, 6)
    svc.checkpoint()

    svc2, _ = make_service(checkpoint_path=ck,
                           admission_kw={"queue_limit": 4})
    assert svc2.resume() == 4
    for i, sid in enumerate(sids):
        info = svc2.info(sid)
        assert info["generation"] == 2 + i
        np.testing.assert_array_equal(
            svc2.grid(sid), expected_grid(SPEC, i, 2 + i))
    # the parked session resumed pending with its debt intact; a pump
    # cycle admits it (no limit gauge in the fresh registry) and pays
    assert svc2.info(queued)["state"] == "pending"
    assert svc2.info(queued)["pending_steps"] == 6
    svc2.pump()
    svc2.pump()
    np.testing.assert_array_equal(svc2.grid(queued),
                                  expected_grid(SPEC, 99, 6))


def test_resume_requires_empty_service(tmp_path):
    ck = str(tmp_path / "s.npz")
    svc, _ = make_service(checkpoint_path=ck)
    svc.create("t", SPEC, fill=FILL)
    svc.checkpoint()
    with pytest.raises(RuntimeError, match="empty"):
        svc.resume()


# -- the acceptance e2e -------------------------------------------------------


def test_e2e_thousand_sessions_few_lanes():
    """ISSUE-12 acceptance: >= 1000 concurrent sessions across >= 3 spec
    families on <= 8 lanes, every one bit-identical to its dedicated
    oracle, zero post-warm retraces, per-tenant step counters and the
    queue-depth gauge on the exposition."""
    svc, reg = make_service(ladder=(1, 8, 64, 256))
    for f in FAMILIES:
        svc.warm(f)
    N = 1000
    sids, gens = [], []
    with retrace_budget(0, context="serve e2e"):
        for i in range(N):
            sids.append(svc.create(f"tenant{i % 4}", FAMILIES[i % 3],
                                   fill=FILL, rng_seed=i)["sid"])
        for i, sid in enumerate(sids):
            n = 1 + i % 4
            svc.step(sid, n, pump=False)  # credit debt; one pump below
            gens.append(n)
        svc.pump()
    lanes = svc.lane_stats()
    assert len(lanes) <= 8, f"{len(lanes)} lanes for {N} sessions"
    assert len({ln["family"] for ln in lanes}) == 3
    assert svc.counts()["sessions"]["live"] == N
    for i, sid in enumerate(sids):
        assert np.array_equal(
            svc.grid(sid), expected_grid(FAMILIES[i % 3], i, gens[i])), \
            f"session {i} diverged from its oracle"
    text = render_prometheus(reg.snapshot())
    for t in range(4):
        line = next(ln for ln in text.splitlines() if ln.startswith(
            f'goltpu_session_steps_total{{tenant="tenant{t}"}}'))
        assert float(line.split()[-1]) > 0
    assert "goltpu_session_queue_depth 0" in text
    assert 'goltpu_sessions_live{tenant="tenant0"} 250' in text


def test_e2e_thousand_mixed_geometry_sessions_one_pool():
    """ISSUE-20 acceptance: 1000 sessions of MIXED logical geometry
    (32x32 torus, 64x32 torus, 16x32 dead — one rule) pack onto ONE
    tile pool and step through a single warm executable with zero
    post-warm retraces, every one bit-identical to its oracle, with the
    pool gauges on the exposition."""
    paged_families = (
        {"rule": "B3/S23", "height": 32, "width": 32, "topology": "torus"},
        {"rule": "B3/S23", "height": 64, "width": 32, "topology": "torus"},
        {"rule": "B3/S23", "height": 16, "width": 32, "topology": "dead"},
    )
    svc, reg = make_service(
        ladder=(1, 8, 64, 256), paged=True,
        paged_opts={"tile_rows": 16, "tile_words": 1, "capacity": 3000})
    for f in paged_families:
        svc.warm(f)
    # mixed geometries AND topologies share one pool -> one executable
    assert len(svc._tile_pools) == 1
    N = 1000
    sids, gens = [], []
    with retrace_budget(0, context="paged serve e2e"):
        for i in range(N):
            sids.append(svc.create(f"tenant{i % 4}", paged_families[i % 3],
                                   fill=FILL, rng_seed=i)["sid"])
        for i, sid in enumerate(sids):
            n = 1 + i % 4
            svc.step(sid, n, pump=False)
            gens.append(n)
        svc.pump()
    assert len(svc._tile_pools) == 1
    assert svc.counts()["sessions"]["live"] == N
    for i, sid in enumerate(sids):
        assert np.array_equal(
            svc.grid(sid), expected_grid(paged_families[i % 3], i, gens[i])), \
            f"session {i} diverged from its oracle"
    text = render_prometheus(reg.snapshot())
    assert 'goltpu_pool_tiles_in_use{pool="serve:B3/S23"}' in text
    assert 'goltpu_pool_tiles_free{pool="serve:B3/S23"}' in text
    pool = next(iter(svc._tile_pools.values()))
    assert pool.in_use() > 0
    # closes hand every page back to the free list
    for sid in sids:
        svc.close(sid)
    assert pool.in_use() == 0


# -- the HTTP frontend --------------------------------------------------------


def _req(port, method, path, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            raw = r.read()
            if r.headers.get("Content-Type", "").startswith(
                    "application/json"):
                return r.status, json.loads(raw)
            return r.status, raw.decode()
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_frontend_http_roundtrip(tmp_path):
    svc, _ = make_service(checkpoint_path=str(tmp_path / "s.npz"))
    with SessionFrontend(svc, 0) as fe:
        code, info = _req(fe.port, "POST", "/sessions", {
            "tenant": "acme", "spec": SPEC, "fill": FILL, "rng_seed": 7})
        assert code == 201 and info["state"] == "packed"
        sid = info["sid"]
        code, info = _req(fe.port, "POST", f"/sessions/{sid}/step",
                          {"n": 5})
        assert code == 200 and info["generation"] == 5
        code, g = _req(fe.port, "GET", f"/sessions/{sid}/grid")
        assert code == 200 and g["encoding"] == "packed_le_u32_hex"
        words = decode_words(g["cells_hex"], g["height"], g["width"] // 32)
        from gameoflifewithactors_tpu.ops import bitpack

        np.testing.assert_array_equal(bitpack.unpack_np(words),
                                      expected_grid(SPEC, 7, 5))
        code, h = _req(fe.port, "GET", "/healthz")
        assert code == 200 and h["ok"] and h["sessions"]["live"] == 1
        code, text = _req(fe.port, "GET", "/metrics")
        assert code == 200
        assert 'goltpu_session_steps_total{tenant="acme"} 5' in text
        code, ck = _req(fe.port, "POST", "/admin/checkpoint")
        assert code == 200 and ck["path"].endswith("s.npz")
        # error mapping: 404 unknown sid, 400 bad payload
        assert _req(fe.port, "GET", "/sessions/ghost")[0] == 404
        assert _req(fe.port, "POST", "/sessions",
                    {"spec": {"rule": "brain", "height": 16,
                              "width": 32}})[0] == 400
        code, info = _req(fe.port, "DELETE", f"/sessions/{sid}")
        assert code == 200 and info["state"] == "closed"

    # the checkpoint written over HTTP resumes a fresh service
    svc2, _ = make_service(checkpoint_path=str(tmp_path / "s.npz"))
    assert svc2.resume() == 1
    np.testing.assert_array_equal(svc2.grid(sid), expected_grid(SPEC, 7, 5))


def test_frontend_maps_admission_reject_to_429():
    svc, reg = make_service(admission_kw={"queue_limit": 0})
    DeviceSampler(registry=reg,
                  backend=lambda: fake_device(2 ** 30, 2 ** 30)).sample_once()
    with SessionFrontend(svc, 0) as fe:
        code, err = _req(fe.port, "POST", "/sessions",
                         {"spec": SPEC, "fill": FILL})
        assert code == 429 and "HBM" in err["error"]


# -- manifest lane entries ----------------------------------------------------


def test_manifest_lane_entries_load_and_validate(tmp_path):
    from gameoflifewithactors_tpu.aot.warmup import (
        load_manifest, load_manifest_entries)

    path = tmp_path / "m.json"
    path.write_text(json.dumps([
        {"rule": "B3/S23", "shape": [16, 32], "backend": "packed",
         "lanes": [1, 2]},
        {"rule": "B36/S23", "shape": [16, 32], "backend": "packed"},
    ]))
    entries = load_manifest_entries(str(path))
    assert entries[0][1] == {"lanes": [1, 2]}
    assert entries[1][1] == {}
    # the extras never reach EngineSpec (which rejects unknown keys)
    assert load_manifest(str(path))[0].rule == "B3/S23"
    path.write_text(json.dumps(
        [{"rule": "B3/S23", "shape": [16, 32], "lanes": [0]}]))
    with pytest.raises(ValueError, match="positive batch capacities"):
        load_manifest_entries(str(path))


def test_warmup_spec_warms_lane_ladder(tmp_path):
    from gameoflifewithactors_tpu.aot.spec import EngineSpec
    from gameoflifewithactors_tpu.aot.warmup import warmup_spec

    spec = EngineSpec.from_dict({"rule": "B3/S23", "shape": [16, 32],
                                 "backend": "packed"})
    row = warmup_spec(spec, aot=False, lanes=[1, 2])
    assert row["lanes"]["capacities"] == [1, 2]
    assert row["lanes"]["status"].startswith("warmed 2 capacities")
    # a lane-warmed ladder serves a fresh service with zero compiles
    svc, _ = make_service(ladder=(1, 2), warm_on_first_use=False)
    with retrace_budget(0, context="manifest-warmed ladder"):
        sid = svc.create("t", SPEC, fill=FILL, rng_seed=1)["sid"]
        svc.step(sid, 2)
    np.testing.assert_array_equal(svc.grid(sid), expected_grid(SPEC, 1, 2))


def test_warmup_reports_unsupported_lane_family():
    from gameoflifewithactors_tpu.aot.spec import EngineSpec
    from gameoflifewithactors_tpu.aot.warmup import warmup_spec

    spec = EngineSpec.from_dict({"rule": "brain", "shape": [16, 32],
                                 "backend": "packed"})
    row = warmup_spec(spec, aot=False, lanes=[1])
    assert row["lanes"]["status"].startswith("unsupported:")
