"""Engine / GridCoordinator / TickScheduler / renderer behavior tests.

These exercise the reference-shaped surface (SURVEY.md §1 API-boundary row):
construct → tick → snapshot/subscribe, across backends and meshes.
"""

import io
import threading

import jax
import numpy as np
import pytest

from gameoflifewithactors_tpu import (
    Engine,
    GridCoordinator,
    TickScheduler,
)
from gameoflifewithactors_tpu.models import seeds
from gameoflifewithactors_tpu.ops.stencil import Topology
from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
from gameoflifewithactors_tpu.utils.metrics import BufferSink, MetricsLogger
from gameoflifewithactors_tpu.utils.render import ConsoleRenderer


@pytest.mark.parametrize("backend", ["packed", "dense"])
def test_engine_step_and_snapshot(backend):
    g = seeds.seeded((16, 32), "glider", 2, 2)
    e = Engine(g, "conway", backend=backend)
    e.step(4)
    assert e.generation == 4
    np.testing.assert_array_equal(e.snapshot(), np.roll(g, (1, 1), (0, 1)))
    assert e.population() == 5


def test_engine_sharded_backend():
    m = mesh_lib.make_mesh((2, 4))
    g = seeds.seeded((16, 256), "glider", 2, 2)
    e = Engine(g, "conway", mesh=m)
    e.step(4)
    np.testing.assert_array_equal(e.snapshot(), np.roll(g, (1, 1), (0, 1)))


def test_engine_rejects_bad_args():
    with pytest.raises(ValueError):
        Engine(np.zeros((4, 32), np.uint8), "conway", backend="warp")
    with pytest.raises(ValueError):
        Engine(np.zeros((4, 4, 4), np.uint8), "conway")
    e = Engine(np.zeros((4, 32), np.uint8), "conway")
    with pytest.raises(ValueError):
        e.step(-1)
    e.step(0)
    assert e.generation == 0


def test_engine_snapshot_downsample_keeps_sparse_life():
    g = seeds.seeded((64, 64), "glider", 1, 1)
    e = Engine(g, "conway")
    view = e.snapshot(max_shape=(8, 8))
    assert view.shape == (8, 8)
    assert view.sum() >= 1  # block-max: the lone glider must stay visible


def test_engine_snapshot_downsample_keeps_edge_cells():
    # regression: edge rows/cols must land in a partial block, not be cropped
    g = np.zeros((100, 64), np.uint8)
    g[-1, :] = 1
    e = Engine(g, "conway")
    e.step(0)
    view = np.asarray(e.snapshot(max_shape=(40, 80)))
    assert view[-1].sum() > 0


def test_engine_mesh_divisibility_error_in_cell_units():
    m = mesh_lib.make_mesh((1, 4), jax.devices()[:4])
    with pytest.raises(ValueError, match=r"width % 128"):
        Engine(np.zeros((64, 64), np.uint8), "conway", mesh=m)


def test_engine_set_grid_shape_check():
    e = Engine(np.zeros((8, 32), np.uint8), "conway")
    with pytest.raises(ValueError):
        e.set_grid(np.zeros((8, 64), np.uint8))


def test_coordinator_centers_seed_and_runs():
    c = GridCoordinator((32, 64), "conway", seed="blinker")
    pop0 = c.population()
    c.tick()
    assert c.generation == 1
    assert c.population() == pop0 == 3


def test_coordinator_random_fill_and_conflict():
    c = GridCoordinator((64, 64), "conway", random_fill=0.5, rng_seed=1)
    assert 0.4 < c.population() / (64 * 64) < 0.6
    with pytest.raises(ValueError):
        GridCoordinator((8, 32), "conway", seed="glider", random_fill=0.5)


def test_coordinator_subscribe_and_frames():
    frames = []
    c = GridCoordinator((16, 32), "conway", seed="glider", track_population=True,
                        view_shape=(8, 8))
    unsub = c.subscribe(frames.append)
    c.run(8, render_every=2)
    assert [f.generation for f in frames] == [2, 4, 6, 8]
    assert all(f.population == 5 for f in frames)
    assert frames[0].grid.shape == (8, 8)
    assert frames[0].full_shape == (16, 32)
    unsub()
    c.tick()
    assert len(frames) == 4  # unsubscribed: no more frames


def test_coordinator_metrics():
    buf = BufferSink()
    c = GridCoordinator((32, 32), "conway", random_fill=0.3,
                        metrics=MetricsLogger(buf), track_population=True)
    c.run(10, render_every=5)
    assert len(buf.records) == 2
    r = buf.records[-1]
    assert r.generation == 10 and r.generations_stepped == 5
    assert r.cell_updates_per_sec > 0
    assert r.population is not None
    # non-sparse backend: no active-tile figure (and the dict omits it)
    assert r.active_tiles is None and "active_tiles" not in r.to_dict()


def test_coordinator_metrics_sparse_active_tiles():
    # sparse backends surface the activity count — the number that
    # explains why a huge mostly-dead universe is cheap
    buf = BufferSink()
    c = GridCoordinator((64, 256), "conway", seed="gosper_gun",
                        backend="sparse",
                        sparse_opts={"tile_rows": 16, "tile_words": 1},
                        topology=Topology.DEAD,
                        metrics=MetricsLogger(buf))
    c.run(8, render_every=8)
    r = buf.records[-1]
    assert r.active_tiles is not None
    assert 0 < r.active_tiles < (64 // 16) * (256 // 32)
    assert r.to_dict()["active_tiles"] == r.active_tiles


def test_scheduler_run_and_controls():
    c = GridCoordinator((16, 32), "conway", seed="glider")
    s = TickScheduler(c)
    assert s.run(max_generations=12) == 12
    assert c.generation == 12

    s2 = TickScheduler(c, generations_per_tick=5)
    assert s2.run(max_generations=12) == 12  # clamps the last tick
    assert c.generation == 24


def test_scheduler_pause_resume_stop_threaded():
    c = GridCoordinator((16, 32), "conway", seed="glider")
    s = TickScheduler(c, rate_hz=500.0)
    t = threading.Thread(target=s.run)
    s.pause()
    t.start()
    gen_while_paused = c.generation
    s.step_once()
    assert c.generation == gen_while_paused + 1
    s.resume()
    while c.generation < gen_while_paused + 3:
        pass
    s.stop()
    t.join(timeout=5)
    assert not t.is_alive()


def test_scheduler_completed_run_returns_even_if_paused():
    # regression: pausing at the finish line must not hang run()
    c = GridCoordinator((16, 32), "conway", seed="glider")
    s = TickScheduler(c)
    s.pause()
    t = threading.Thread(target=lambda: s.run(max_generations=0))
    t.start()
    t.join(timeout=5)
    assert not t.is_alive()


def test_scheduler_validation():
    c = GridCoordinator((8, 32), "conway")
    with pytest.raises(ValueError):
        TickScheduler(c, rate_hz=0)
    with pytest.raises(ValueError):
        TickScheduler(c, generations_per_tick=0)


def test_console_renderer_output():
    c = GridCoordinator((8, 32), "conway", seed="block", track_population=True)
    out = io.StringIO()
    c.subscribe(ConsoleRenderer(out, ansi=False, charset=".#"))
    c.tick()
    text = out.getvalue()
    assert "##" in text
    assert "gen 1" in text and "pop 4" in text
    with pytest.raises(ValueError):
        ConsoleRenderer(out, charset="#")  # need at least (dead, alive)


def test_engine_pallas_backend():
    g = seeds.seeded((32, 64), "glider", 2, 2)
    e = Engine(g, "conway", backend="pallas")
    e.step(8)
    np.testing.assert_array_equal(e.snapshot(), np.roll(g, (2, 2), (0, 1)))
    assert e.population() == 5
    # pallas + mesh is the row-band runner; 2D meshes flatten into nx*ny
    # full-width bands (tests/test_sharding.py TestShardedPallas pins the
    # bit-identity; here just the routing)
    e2d = Engine(np.zeros((16, 256), np.uint8), "conway", backend="pallas",
                 mesh=mesh_lib.make_mesh((2, 4)))
    assert e2d.backend == "pallas" and e2d._banded
    e2d.step(2)
    assert e2d.population() == 0


def test_auto_backend_resolution_off_tpu():
    # tests force the CPU backend, so auto must resolve to packed — the
    # pallas pick only happens on a real TPU (covered by the TPU worklist)
    import numpy as np

    e = Engine(np.zeros((16, 32), np.uint8), "B3/S23")
    assert e.backend == "packed"
    e2 = Engine(np.zeros((16, 32), np.uint8), "brain")  # multi-state
    assert e2.backend == "packed"
    with pytest.raises(ValueError, match="backend must be"):
        Engine(np.zeros((16, 32), np.uint8), "B3/S23", backend="warp")


def test_ppm_sequence_subscriber(tmp_path):
    # the RenderFrame-subscriber form writes the (possibly downsampled)
    # frame view, numbered by generation, with the stem's extension
    from gameoflifewithactors_tpu.utils.render import PpmSequenceWriter

    c = GridCoordinator((16, 32), "conway", seed="glider",
                        view_shape=(8, 16))
    seq = PpmSequenceWriter(str(tmp_path / "f.ppm"))
    c.subscribe(seq)
    c.run(4, render_every=2)
    assert [p.rsplit("_", 1)[1] for p in seq.paths] == [
        "000002.ppm", "000004.ppm"]
    data = (tmp_path / "f_000002.ppm").read_bytes()
    assert data.startswith(b"P6\n16 8\n255\n")   # the downsampled view
