"""goltpu-lint tests: golden fixtures per rule, pragma/baseline
semantics, the CLI exit-code contract, and the whole-tree "this repo is
clean" smoke (the gate .github/workflows/tier1.yml enforces).

Everything here drives the engine through ``lint_source``/``lint_paths``
on in-memory fixtures — no jax, no device, no engine builds — except the
CLI contract tests, which run ``scripts/lint.py`` as a subprocess (one
of them under a poisoned ``jax`` module, pinning the "lints with no jax
installed" guarantee the CI job relies on).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from gameoflifewithactors_tpu.analysis import lint as lint_lib
from gameoflifewithactors_tpu.analysis.lint import (
    PRAGMA_ERROR_CODE,
    RULES,
    lint_source,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "scripts", "lint.py")


def codes(report, only=None) -> list:
    """Finding codes, optionally filtered to the rule under test (a
    fixture exercising GOL001 with @jax.jit legitimately also trips
    GOL006 — bare jax.jit — which is not what that fixture asserts)."""
    out = [f.code for f in report.findings]
    return [c for c in out if c == only] if only else out


def run_fixture(src: str, path: str = "pkg/mod.py"):
    return lint_source(textwrap.dedent(src), path)


# -- registry sanity ----------------------------------------------------------


def test_rule_registry_is_complete_and_stable():
    assert sorted(RULES) == [
        "GOL001", "GOL002", "GOL003", "GOL004", "GOL005", "GOL006",
        "GOL007", "GOL008"]
    assert sorted(lint_lib.PROJECT_RULES) == ["GOL009", "GOL010"]
    for rule in (*RULES.values(), *lint_lib.PROJECT_RULES.values()):
        assert rule.name and rule.summary
    # per-file and project registries share one code namespace
    assert not set(RULES) & set(lint_lib.PROJECT_RULES)


# -- GOL001: host sync in traced bodies ---------------------------------------


def test_gol001_positive_item_and_float_in_jit():
    rep = run_fixture("""
        import jax

        @jax.jit
        def f(x):
            v = x.sum().item()
            return float(x) + v
    """)
    assert codes(rep, "GOL001") == ["GOL001", "GOL001"]


def test_gol001_positive_print_and_asarray_in_lax_body():
    rep = run_fixture("""
        import jax
        import numpy as np

        def body(carry, x):
            print(carry)
            return np.asarray(carry), x

        def outer(xs):
            return jax.lax.scan(body, 0, xs)
    """)
    assert codes(rep, "GOL001") == ["GOL001", "GOL001"]


def test_gol001_negative_static_args_and_host_code():
    rep = run_fixture("""
        import jax
        import numpy as np
        from functools import partial

        @partial(jax.jit, static_argnames=("n",))
        def f(x, n):
            return x * float(n)          # n is static: trace-time float

        def host(x):
            print(x)                     # not a traced body
            return np.asarray(x)

        @jax.jit
        def g(x):
            jax.debug.print("{}", x)     # the sanctioned in-jit print
            return x
    """)
    assert codes(rep, "GOL001") == []


def test_gol001_optionally_donated_defaults_rule_topology_static():
    rep = run_fixture("""
        from ._jit import optionally_donated

        @optionally_donated("state")
        def step(state, rule, topology):
            return state if float(rule.radius) else state
    """)
    # float(rule.radius) is fine — rule is static by the decorator's
    # default; float(state) would not be
    assert codes(rep) == []


# -- GOL002: traced branching -------------------------------------------------


def test_gol002_positive_if_and_while_on_traced_param():
    rep = run_fixture("""
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            while x < 3:
                x = x + 1
            return x
    """)
    assert codes(rep, "GOL002") == ["GOL002", "GOL002"]


def test_gol002_negative_static_shape_isinstance_and_none():
    rep = run_fixture("""
        import jax

        @jax.jit
        def f(x, mask=None):
            if x.shape[0] > 8:           # shapes are trace-time constants
                return x
            if isinstance(x, tuple):     # python-level type probe
                return x[0]
            if mask is None:             # identity test is static
                return x
            return x + mask
    """)
    assert codes(rep, "GOL002") == []


def test_gol002_shard_map_body_is_traced():
    rep = run_fixture("""
        from functools import partial
        from jax.experimental.shard_map import shard_map

        @partial(shard_map, mesh=None, in_specs=(), out_specs=())
        def _run(tile, n):
            if n:
                return tile
            return tile
    """)
    assert codes(rep, "GOL002") == ["GOL002"]


# -- GOL003: unconditional donation -------------------------------------------


def test_gol003_positive_constant_donation():
    rep = run_fixture("""
        import jax
        from functools import partial

        f = jax.jit(lambda x: x, donate_argnums=(0,))

        @partial(jax.jit, donate_argnames=("state",))
        def g(state):
            return state
    """)
    assert codes(rep, "GOL003") == ["GOL003", "GOL003"]


def test_gol003_negative_opt_in_or_empty():
    rep = run_fixture("""
        import jax

        def make(fun, donate=False):
            return jax.jit(fun, donate_argnums=(0,) if donate else ())
    """, path="pkg/ops/_jit.py")  # choke point: GOL006 exempt here too
    assert codes(rep) == []


# -- GOL004: obs/ lock discipline ---------------------------------------------


_LOCKED_CLS = """
    import threading

    class Recorder:
        def __init__(self):
            self._events = []
            self._lock = threading.Lock()

        def record(self, ev):
            {record_body}
"""


def test_gol004_positive_mutation_outside_lock():
    rep = run_fixture(
        textwrap.dedent(_LOCKED_CLS).format(
            record_body="self._events.append(ev)"),
        path="pkg/obs/rec.py")
    assert codes(rep) == ["GOL004"]


def test_gol004_negative_under_lock_or_elsewhere():
    body = "with self._lock:\n                self._events.append(ev)"
    rep = run_fixture(
        textwrap.dedent(_LOCKED_CLS).format(record_body=body),
        path="pkg/obs/rec.py")
    assert codes(rep) == []
    # same slip outside obs/ is out of scope for this rule
    rep = run_fixture(
        textwrap.dedent(_LOCKED_CLS).format(
            record_body="self._events.append(ev)"),
        path="pkg/utils/rec.py")
    assert codes(rep) == []


def test_gol004_lockless_class_is_exempt():
    rep = run_fixture("""
        class Plain:
            def __init__(self):
                self._items = []

            def add(self, x):
                self._items.append(x)
    """, path="pkg/obs/plain.py")
    assert codes(rep) == []


# -- GOL005: wall-clock timing ------------------------------------------------


def test_gol005_positive_and_negative():
    rep = run_fixture("""
        import time

        def f():
            t0 = time.time()
            t1 = time.perf_counter()
            return t0, t1
    """)
    assert codes(rep) == ["GOL005"]


# -- GOL006: untracked jit ----------------------------------------------------


def test_gol006_positive_everywhere_but_the_choke_point():
    src = """
        import jax

        run = jax.jit(lambda x: x)
    """
    assert codes(run_fixture(src)) == ["GOL006"]
    assert codes(run_fixture(src, path="pkg/ops/_jit.py")) == []


def test_gol006_tracked_jit_is_clean():
    rep = run_fixture("""
        from ._jit import tracked_jit

        run = tracked_jit(lambda x: x, runner="r")
    """)
    assert codes(rep) == []


# -- GOL007: obs/ scrape-cache read discipline --------------------------------


_CACHED_CLS = """
    import threading

    class Agg:
        def __init__(self):
            self._cache = None
            self._lock = threading.Lock()

        def scrape(self):
            {body}
"""


def test_gol007_positive_lock_free_cache_read():
    rep = run_fixture(
        textwrap.dedent(_CACHED_CLS).format(body="return self._cache"),
        path="pkg/obs/agg.py")
    assert codes(rep, "GOL007") == ["GOL007"]


def test_gol007_negative_snapshot_under_lock():
    body = ("with self._lock:\n"
            "                c = self._cache\n"
            "            return c")
    rep = run_fixture(
        textwrap.dedent(_CACHED_CLS).format(body=body),
        path="pkg/obs/agg.py")
    assert codes(rep, "GOL007") == []


def test_gol007_out_of_scope_paths_and_attrs_are_exempt():
    # same slip outside obs/ is out of scope for this rule
    rep = run_fixture(
        textwrap.dedent(_CACHED_CLS).format(body="return self._cache"),
        path="pkg/serve/agg.py")
    assert codes(rep, "GOL007") == []
    # non-cache attrs are GOL004's (write-side) business, not GOL007's
    rep = run_fixture("""
        import threading

        class Rec:
            def __init__(self):
                self._events = []
                self._lock = threading.Lock()

            def peek(self):
                return self._events
    """, path="pkg/obs/rec.py")
    assert codes(rep, "GOL007") == []


# -- pragmas ------------------------------------------------------------------


def test_pragma_suppresses_same_line_and_line_above():
    rep = run_fixture("""
        import time

        a = time.time()  # goltpu: ignore[GOL005] -- epoch stamp for a report header
        # goltpu: ignore[GOL005] -- epoch stamp, standalone form
        b = time.time()
    """)
    assert codes(rep) == []
    assert [f.code for f in rep.suppressed] == ["GOL005", "GOL005"]


def test_pragma_without_reason_is_its_own_finding_and_suppresses_nothing():
    rep = run_fixture("""
        import time

        a = time.time()  # goltpu: ignore[GOL005]
    """)
    assert codes(rep) == [PRAGMA_ERROR_CODE, "GOL005"]


def test_pragma_with_unknown_code_is_flagged():
    rep = run_fixture("""
        x = 1  # goltpu: ignore[BOGUS] -- not a real code
    """)
    assert codes(rep) == [PRAGMA_ERROR_CODE]


def test_pragma_wrong_code_does_not_suppress():
    rep = run_fixture("""
        import time

        a = time.time()  # goltpu: ignore[GOL006] -- wrong code on purpose
    """)
    assert codes(rep) == ["GOL005"]


def test_pragma_only_matches_comments_not_strings():
    rep = run_fixture('''
        DOC = "say # goltpu: ignore[GOLnnn] -- reason to suppress"
    ''')
    assert codes(rep) == []


# -- baseline -----------------------------------------------------------------


def test_baseline_grandfathers_by_code_path_message(tmp_path):
    mod = tmp_path / "legacy.py"
    mod.write_text("import time\nt = time.time()\n")
    rep = lint_source(mod.read_text(), str(mod))
    assert codes(rep) == ["GOL005"]
    baseline = [rep.findings[0].to_dict()]
    res = lint_lib.lint_paths([str(mod)], baseline=baseline)
    assert res.ok and not res.findings
    assert [f.code for f in res.baselined] == ["GOL005"]
    # a fixed finding leaves its baseline entry stale — reported, not ok'd
    mod.write_text("import time\nt = time.perf_counter()\n")
    res = lint_lib.lint_paths([str(mod)], baseline=baseline)
    assert res.ok and len(res.unused_baseline) == 1


def test_malformed_baseline_raises(tmp_path):
    bad = tmp_path / "b.json"
    bad.write_text('{"version": 99}')
    with pytest.raises(lint_lib.BaselineError):
        lint_lib.load_baseline(str(bad))


# -- CLI contract -------------------------------------------------------------


def _cli(args, env=None, cwd=None):
    e = dict(os.environ)
    if env:
        e.update(env)
    return subprocess.run([sys.executable, CLI, *args],
                          capture_output=True, text=True, env=e,
                          cwd=cwd or REPO)


def test_cli_exit_0_on_clean_file(tmp_path):
    f = tmp_path / "clean.py"
    f.write_text("x = 1\n")
    r = _cli([str(f), "--baseline", "none"])
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_exit_1_on_findings_and_json_shape(tmp_path):
    f = tmp_path / "dirty.py"
    f.write_text("import time\nt = time.time()\n")
    r = _cli([str(f), "--baseline", "none", "--json"])
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert doc["exit_code"] == 1 and not doc["ok"]
    assert [x["code"] for x in doc["findings"]] == ["GOL005"]


def test_cli_exit_2_on_bad_input(tmp_path):
    assert _cli([str(tmp_path / "missing.py"),
                 "--baseline", "none"]).returncode == 2
    broken = tmp_path / "broken.py"
    broken.write_text("def (:\n")
    assert _cli([str(broken), "--baseline", "none"]).returncode == 2
    badbase = tmp_path / "b.json"
    badbase.write_text("[]")
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert _cli([str(clean), "--baseline", str(badbase)]).returncode == 2


def test_cli_runs_without_jax(tmp_path):
    """The CI lint job runs before any pip install: a poisoned ``jax``
    module on the path proves the CLI never imports it."""
    poison = tmp_path / "site"
    poison.mkdir()
    (poison / "jax.py").write_text(
        "raise ImportError('jax must not be imported by the linter')\n")
    f = tmp_path / "dirty.py"
    f.write_text("import time\nt = time.time()\n")
    r = _cli([str(f), "--baseline", "none"],
             env={"PYTHONPATH": str(poison)})
    assert r.returncode == 1, r.stdout + r.stderr
    assert "GOL005" in r.stdout


# -- the repo itself ----------------------------------------------------------


def test_whole_tree_is_clean_under_committed_baseline():
    """The acceptance gate: the shipped tree — package, scripts/,
    tests/ and examples/ — lints clean with the committed (empty)
    baseline; every suppression in the tree is an inline pragma with a
    written reason."""
    r = _cli(["gameoflifewithactors_tpu", "scripts", "tests", "examples",
              "--json"])
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["ok"] and not doc["findings"]
    assert doc["files_scanned"] > 50
    # the committed baseline stays EMPTY (satellite contract): findings
    # are fixed or pragma'd, never grandfathered
    with open(os.path.join(REPO, "lint_baseline.json")) as f:
        assert json.load(f)["findings"] == []


# -- pragma parsing on newer syntax -------------------------------------------


def test_pragma_on_walrus_statement():
    rep = run_fixture("""
        import time
        if (t := time.time()) > 0:  # goltpu: ignore[GOL005] -- epoch wanted
            pass
    """)
    assert codes(rep) == []
    assert [f.code for f in rep.suppressed] == ["GOL005"]


def test_pragma_inside_match_statement():
    rep = run_fixture("""
        import time

        def route(cmd):
            match cmd:
                case "now":
                    # goltpu: ignore[GOL005] -- epoch stamp for a report header
                    return time.time()
                case _:
                    return time.time()
    """)
    assert codes(rep) == ["GOL005"]  # only the un-pragma'd case arm
    assert [f.code for f in rep.suppressed] == ["GOL005"]


def test_pragma_above_decorated_async_def():
    """A standalone pragma line above a decorator must suppress findings
    anchored on the (async) def it decorates — decorator lines sit
    between the pragma and the def's lineno."""
    rep = run_fixture("""
        import functools
        import jax

        # goltpu: ignore[GOL003] -- fixture: decorated async entry point
        @functools.partial(jax.jit, donate_argnums=(0,), static_argnums=(1,))
        async def consume(buf, n):
            return buf
    """)
    assert codes(rep, "GOL003") == []
    assert "GOL003" in [f.code for f in rep.suppressed]


# -- baseline round-trip (order independence) ---------------------------------


def test_write_baseline_then_baseline_round_trips_to_exit_0(tmp_path):
    """Property: for ANY dirty tree, `--write-baseline` followed by
    `--baseline <file>` exits 0 — regardless of finding order or how
    findings distribute over files."""
    names = ["zz.py", "aa.py", "mm.py"]
    bodies = [
        "import time\nt = time.time()\nu = time.time()\n",
        "import jax\nrun = jax.jit(lambda x: x)\n",
        "import time\n\n\ndef f():\n    return time.time()\n",
    ]
    for name, body in zip(names, bodies):
        (tmp_path / name).write_text(body)
    base = tmp_path / "base.json"
    r = _cli([str(tmp_path), "--baseline", str(base), "--write-baseline"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert json.loads(base.read_text())["findings"]
    # every recorded finding matches on re-lint: exit 0, nothing stale
    r = _cli([str(tmp_path), "--baseline", str(base), "--strict-baseline",
              "--json"])
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["ok"] and not doc["findings"]


# -- SARIF export -------------------------------------------------------------


def test_cli_sarif_output_shape(tmp_path):
    f = tmp_path / "dirty.py"
    f.write_text("import time\nt = time.time()\n")
    out = tmp_path / "out.sarif"
    r = _cli([str(f), "--baseline", "none", "--sarif", str(out)])
    assert r.returncode == 1
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    rule_ids = [x["id"] for x in run["tool"]["driver"]["rules"]]
    assert "GOL001" in rule_ids and "GOL010" in rule_ids
    (res,) = run["results"]
    assert res["ruleId"] == "GOL005"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == 2
