"""Driver-contract regression tests for __graft_entry__.py.

Round 1's MULTICHIP artifact recorded rc=124 because dryrun_multichip let
computation fall onto the default (wedged-tunnel) backend. These tests run
the entry points exactly as the driver does — fresh subprocess, virtual-CPU
device count forced via env — and must stay green regardless of tunnel
state. A generous timeout stands in for the driver's watchdog: a hang here
IS the round-1 bug.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code: str, env_extra: dict, timeout: float = 240.0):
    env = {**os.environ, **env_extra}
    return subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=timeout, cwd=REPO, env=env)


def test_dryrun_multichip_driver_style():
    # as the driver invokes it: its own env staging, 8 virtual CPU devices
    r = _run(
        "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8); print('OK')",
        {"XLA_FLAGS": "--xla_force_host_platform_device_count=8",
         "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr[-2000:]
    assert "OK" in r.stdout


def test_dryrun_multichip_bare_env():
    # no driver staging at all — the entry point must stage everything itself
    r = _run(
        "from __graft_entry__ import dryrun_multichip; dryrun_multichip(4); print('OK')",
        {})
    assert r.returncode == 0, r.stderr[-2000:]
    assert "OK" in r.stdout


def test_standalone_self_test():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "__graft_entry__.py")],
        capture_output=True, text=True, timeout=240, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "entry():" in r.stdout and "dryrun_multichip(8): ok" in r.stdout


def test_dryrun_leaves_env_clean_for_children():
    # VERDICT round-1 Weak #2 regression: after dryrun, a child process must
    # not inherit a CPU pin (a driver running bench.py next would silently
    # record a CPU number as TPU evidence)
    r = _run(
        "import os\n"
        "before = {k: os.environ.get(k) for k in ('JAX_PLATFORMS', 'XLA_FLAGS')}\n"
        "from __graft_entry__ import dryrun_multichip\n"
        "dryrun_multichip(2)\n"
        "after = {k: os.environ.get(k) for k in ('JAX_PLATFORMS', 'XLA_FLAGS')}\n"
        "assert after == before, (before, after)\n"
        "print('ENV-CLEAN')",
        {})
    assert r.returncode == 0, r.stderr[-2000:]
    assert "ENV-CLEAN" in r.stdout
