"""Golden-image rendering tests across every rule family (VERDICT r4 #7).

The reference's only observability is its console Renderer [SURVEY.md §6
metrics row]; this suite pins the TPU framework's equivalent outputs —
ConsoleRenderer text (plain and ANSI) and save_ppm bytes — against golden
files in tests/golden/, over a deterministic evolution of each family:
binary (Conway pulsar), Generations (Brian's Brain, dying-state glyphs and
grey fade), multi-state C>=3 LtL (decay plane stack), and elementary
(W30 spacetime diagram). A rendering regression (glyph mapping, fade
arithmetic, PPM header, status line) shows up as a byte diff; an engine
regression upstream shows up too, which is intended — the golden is the
end-to-end "what the user sees".

Regenerate after an INTENDED change with:
  python tests/test_render_golden.py --regen
and review the diff before committing.
"""

import io
import os

import numpy as np
import pytest

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")


def _render_text(grid: np.ndarray, generation: int, *, ansi: bool,
                 charset: str = "·█", population=None) -> str:
    from gameoflifewithactors_tpu.coordinator import RenderFrame
    from gameoflifewithactors_tpu.utils.render import ConsoleRenderer

    buf = io.StringIO()
    r = ConsoleRenderer(buf, ansi=ansi, charset=charset)
    r(RenderFrame(grid=grid, generation=generation, population=population,
                  full_shape=tuple(grid.shape)))
    return buf.getvalue()


def _conway_pulsar():
    import jax.numpy as jnp

    from gameoflifewithactors_tpu import Engine
    from gameoflifewithactors_tpu.models import seeds

    grid = np.asarray(seeds.seeded((17, 32), "pulsar", 2, 9))
    e = Engine(grid, "B3/S23")
    e.step(2)                      # pulsar is period 3: gen 2 is distinct
    return e.snapshot(), e.generation, e.population()


def _brain_soup():
    from gameoflifewithactors_tpu import Engine

    rng = np.random.default_rng(42)
    grid = (rng.random((24, 48)) < 0.3).astype(np.uint8)
    e = Engine(grid, "brain")
    e.step(5)
    return e.snapshot(), e.generation


def _ltl_multistate():
    from gameoflifewithactors_tpu import Engine

    rng = np.random.default_rng(7)
    grid = rng.integers(0, 4, size=(32, 64), dtype=np.uint8)
    e = Engine(grid, "R2,C4,M1,S3..8,B5..9")
    e.step(4)
    return e.snapshot(), e.generation


def _w30_spacetime():
    import jax.numpy as jnp

    from gameoflifewithactors_tpu.models.elementary import parse_elementary
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.ops.elementary import evolve_spacetime

    row = np.zeros(64, dtype=np.uint8)
    row[32] = 1                    # single seed -> Sierpinski-like W30 cone
    st = evolve_spacetime(bitpack.pack(jnp.asarray(row[None])), 40,
                          rule=parse_elementary("W30"))
    return np.asarray(bitpack.unpack(st[:, 0, :]))


def _artifacts() -> dict:
    """name -> bytes, every golden in one place (tests and --regen share)."""
    from gameoflifewithactors_tpu.utils.render import save_ppm

    out = {}
    pulsar, gen, pop = _conway_pulsar()
    out["conway_pulsar_g2_plain.txt"] = _render_text(
        pulsar, gen, ansi=False, population=pop).encode()
    out["conway_pulsar_g2_ansi.txt"] = _render_text(
        pulsar, gen, ansi=True, population=pop).encode()

    brain, gen = _brain_soup()
    out["brain_g5_plain.txt"] = _render_text(
        brain, gen, ansi=False, charset="·█▒").encode()

    def ppm_bytes(grid, scale=1):
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".ppm") as f:
            save_ppm(grid, f.name, scale=scale)
            return open(f.name, "rb").read()

    out["brain_g5.ppm"] = ppm_bytes(brain)
    ltl, gen = _ltl_multistate()
    out["ltl_c4_g4.ppm"] = ppm_bytes(ltl)
    out["w30_spacetime.ppm"] = ppm_bytes(_w30_spacetime(), scale=2)
    return out


_EXPECTED = sorted((
    "conway_pulsar_g2_plain.txt", "conway_pulsar_g2_ansi.txt",
    "brain_g5_plain.txt", "brain_g5.ppm", "ltl_c4_g4.ppm",
    "w30_spacetime.ppm",
))


@pytest.fixture(scope="module")
def artifacts():
    return _artifacts()


@pytest.mark.parametrize("name", _EXPECTED)
def test_golden(name, artifacts):
    path = os.path.join(GOLDEN_DIR, name)
    assert os.path.exists(path), (
        f"golden file {name} missing — run `python {__file__} --regen`")
    got = artifacts[name]
    want = open(path, "rb").read()
    assert got == want, (
        f"{name} drifted from its golden ({len(got)} vs {len(want)} bytes); "
        f"if the change is intended, regen and review the diff")


def test_golden_dir_has_no_strays():
    on_disk = sorted(f for f in os.listdir(GOLDEN_DIR)
                     if not f.startswith("."))
    assert on_disk == _EXPECTED


def test_families_visibly_distinct(artifacts):
    # sanity on the goldens themselves: the Brain PPM shows dying states as
    # intermediate greys (>2 luminances), the LtL PPM shows 4 states, and
    # the W30 cone is non-trivial
    def lums(ppm: bytes):
        head_end = ppm.index(b"255\n") + 4
        return set(ppm[head_end::3])

    assert len(lums(artifacts["brain_g5.ppm"])) >= 3
    assert len(lums(artifacts["ltl_c4_g4.ppm"])) >= 4
    body = artifacts["conway_pulsar_g2_plain.txt"].decode()
    assert "█" in body and "·" in body
    ansi = artifacts["conway_pulsar_g2_ansi.txt"].decode()
    assert ansi.startswith("\x1b[2J\x1b[H")


if __name__ == "__main__":
    import sys

    if "--regen" not in sys.argv:
        raise SystemExit("usage: python tests/test_render_golden.py --regen")
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    for name, data in _artifacts().items():
        with open(os.path.join(GOLDEN_DIR, name), "wb") as f:
            f.write(data)
        print(f"wrote {name} ({len(data)} bytes)")
