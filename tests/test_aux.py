"""Aux subsystems: profiling hooks, fault injection + guarded recovery."""

import os

import numpy as np
import pytest

from gameoflifewithactors_tpu import Engine
from gameoflifewithactors_tpu.models import seeds
from gameoflifewithactors_tpu.utils import fault
from gameoflifewithactors_tpu.utils.profiling import PhaseTimer, profile_steps


def test_phase_timer():
    t = PhaseTimer()
    with t.phase("step"):
        pass
    with t.phase("step"):
        pass
    with t.phase("sync"):
        pass
    s = t.summary()
    assert s["step"]["count"] == 2 and s["sync"]["count"] == 1
    assert s["step"]["total_s"] >= 0


def test_profile_steps_writes_trace(tmp_path):
    e = Engine(seeds.seeded((32, 32), "glider", 1, 1), "conway")
    profile_steps(e, 4, str(tmp_path), chunk=2)
    assert e.generation == 4
    # jax wrote a profile tree under the log dir
    walked = [p for p, _, files in os.walk(tmp_path) for f in files]
    assert walked, "no profiler output written"


def test_fault_injectors_change_state():
    g = seeds.seeded((16, 32), "glider", 2, 2)
    e = Engine(g, "conway")
    fault.drop_region(e, 0, 0, 16, 32)
    assert e.population() == 0
    e2 = Engine(g, "conway")
    fault.corrupt_region(e2, 0, 0, 8, 8, seed=3)
    assert not np.array_equal(e2.snapshot(), g)


def test_guarded_run_recovers_bit_exact(tmp_path):
    """Corrupt the universe mid-run; GuardedRun must roll back and land on
    exactly the state an unfaulted run reaches."""
    g = seeds.seeded((32, 64), "gosper_gun", 4, 4)

    clean = Engine(g, "conway")
    clean.step(40)
    want = clean.snapshot()

    e = Engine(g, "conway")
    injected = {"done": False}

    def evil_validator(engine):
        # after gen 20, inject one transient corruption and report failure
        if engine.generation == 20 and not injected["done"]:
            fault.corrupt_region(engine, 0, 0, 8, 8, seed=1)
            injected["done"] = True
            return False
        return True

    guard = fault.GuardedRun(
        e,
        checkpoint_every=10,
        checkpoint_path=str(tmp_path / "g.npz"),
        validator=evil_validator,
    )
    guard.run(40)
    assert guard.recoveries == 1
    assert e.generation == 40
    np.testing.assert_array_equal(e.snapshot(), want)


def test_guarded_run_gives_up_on_persistent_failure(tmp_path):
    e = Engine(seeds.seeded((16, 32), "blinker", 4, 4), "conway")
    guard = fault.GuardedRun(
        e,
        checkpoint_every=5,
        checkpoint_path=str(tmp_path / "g.npz"),
        validator=lambda _: False,  # permanently broken
        max_retries=2,
    )
    with pytest.raises(RuntimeError, match="giving up"):
        guard.run(10)


def test_population_bounds_validator():
    e = Engine(seeds.seeded((16, 32), "glider", 2, 2), "conway")
    assert fault.population_bounds_validator(1, 100)(e)
    assert not fault.population_bounds_validator(6, None)(e)
    assert not fault.population_bounds_validator(0, 4)(e)
