"""Aux subsystems: profiling hooks, fault injection + guarded recovery."""

import os

import numpy as np
import pytest

from gameoflifewithactors_tpu import Engine
from gameoflifewithactors_tpu.models import seeds
from gameoflifewithactors_tpu.utils import fault
from gameoflifewithactors_tpu.utils.profiling import PhaseTimer, profile_steps


def test_phase_timer():
    t = PhaseTimer()
    with t.phase("step"):
        pass
    with t.phase("step"):
        pass
    with t.phase("sync"):
        pass
    s = t.summary()
    assert s["step"]["count"] == 2 and s["sync"]["count"] == 1
    assert s["step"]["total_s"] >= 0


def test_profile_steps_writes_trace(tmp_path):
    e = Engine(seeds.seeded((32, 32), "glider", 1, 1), "conway")
    profile_steps(e, 4, str(tmp_path), chunk=2)
    assert e.generation == 4
    # jax wrote a profile tree under the log dir
    walked = [p for p, _, files in os.walk(tmp_path) for f in files]
    assert walked, "no profiler output written"


def test_perfetto_summary_busiest_track_semantics(tmp_path):
    """The measured-roofline parser: interval-union busy time (nested and
    overlapping slices must not double count), and device numbers taken
    from the single busiest device track — a TPU dump mirrors one device
    across several track layers, so summing them would let the duty cycle
    exceed 1.0."""
    import json

    from gameoflifewithactors_tpu.utils.profiling import perfetto_summary

    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Modules"}},
        {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "host:CPU"}},
        {"ph": "M", "pid": 2, "tid": 9, "name": "thread_name",
         "args": {"name": "python"}},
        # device layer 1: one 100us module slice with a nested 60us slice
        # -> union busy 100, not 160
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 100, "name": "jit_step"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 20, "dur": 60, "name": "fusion"},
        # device layer 2 mirrors the same wall time as separate ops
        {"ph": "X", "pid": 1, "tid": 2, "ts": 0, "dur": 50, "name": "op_a"},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 50, "dur": 40, "name": "op_b"},
        # host track, busier than the device in wall time
        {"ph": "X", "pid": 2, "tid": 9, "ts": 0, "dur": 500, "name": "dispatch"},
    ]
    path = tmp_path / "perfetto_trace.json"
    path.write_text(json.dumps({"traceEvents": events}))
    s = perfetto_summary(str(path))
    assert s["device_tracks"] == 2
    # busiest DEVICE track wins (not the busier host track), union not sum
    assert s["device_track"] == "device:TPU:0/XLA Modules"
    assert s["device_busy_us"] == 100.0
    assert s["device_busy_us"] <= s["device_span_us"]
    host = [t for t in s["tracks"] if t["track"] == "host:CPU/python"]
    assert host and host[0]["busy_us"] == 500.0


def test_fault_injectors_change_state():
    g = seeds.seeded((16, 32), "glider", 2, 2)
    e = Engine(g, "conway")
    fault.drop_region(e, 0, 0, 16, 32)
    assert e.population() == 0
    e2 = Engine(g, "conway")
    fault.corrupt_region(e2, 0, 0, 8, 8, seed=3)
    assert not np.array_equal(e2.snapshot(), g)


def test_guarded_run_recovers_bit_exact(tmp_path):
    """Corrupt the universe mid-run; GuardedRun must roll back and land on
    exactly the state an unfaulted run reaches."""
    g = seeds.seeded((32, 64), "gosper_gun", 4, 4)

    clean = Engine(g, "conway")
    clean.step(40)
    want = clean.snapshot()

    e = Engine(g, "conway")
    injected = {"done": False}

    def evil_validator(engine):
        # after gen 20, inject one transient corruption and report failure
        if engine.generation == 20 and not injected["done"]:
            fault.corrupt_region(engine, 0, 0, 8, 8, seed=1)
            injected["done"] = True
            return False
        return True

    guard = fault.GuardedRun(
        e,
        checkpoint_every=10,
        checkpoint_path=str(tmp_path / "g.npz"),
        validator=evil_validator,
    )
    guard.run(40)
    assert guard.recoveries == 1
    assert e.generation == 40
    np.testing.assert_array_equal(e.snapshot(), want)


def test_guarded_run_gives_up_on_persistent_failure(tmp_path):
    e = Engine(seeds.seeded((16, 32), "blinker", 4, 4), "conway")
    guard = fault.GuardedRun(
        e,
        checkpoint_every=5,
        checkpoint_path=str(tmp_path / "g.npz"),
        validator=lambda _: False,  # permanently broken
        max_retries=2,
    )
    with pytest.raises(RuntimeError, match="giving up"):
        guard.run(10)


def test_population_bounds_validator():
    e = Engine(seeds.seeded((16, 32), "glider", 2, 2), "conway")
    assert fault.population_bounds_validator(1, 100)(e)
    assert not fault.population_bounds_validator(6, None)(e)
    assert not fault.population_bounds_validator(0, 4)(e)


def test_halo_bytes_metric():
    import jax

    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
    from gameoflifewithactors_tpu.utils.metrics import StepMetrics

    # unsharded: no interconnect traffic
    e1 = Engine(seeds.empty((32, 64)), "B3/S23")
    assert e1.halo_bytes_per_gen() == 0

    # 2x4 mesh, packed: per tile 2 row strips of (wq/ny) words + 2 col
    # strips of (h/nx + 2) words, 4 bytes/word, 8 tiles
    m = mesh_lib.make_mesh((2, 4), jax.devices())
    e2 = Engine(seeds.empty((32, 256)), "B3/S23", mesh=m)
    wq, h = 256 // 32, 32
    want = 8 * 2 * ((wq // 4) * 4 + (h // 2 + 2) * 4)
    assert e2.halo_bytes_per_gen() == want

    # a size-1 mesh axis moves nothing over the interconnect (self-copy)
    m18 = mesh_lib.make_mesh((1, 8), jax.devices())
    e3 = Engine(seeds.empty((32, 256)), "B3/S23", mesh=m18)
    col_strip = (32 // 1 + 2) * 4
    assert e3.halo_bytes_per_gen() == 2 * 1 * 8 * col_strip  # columns only

    # DEAD boundary drops the wrap sends: (nx-1) and (ny-1) per direction
    from gameoflifewithactors_tpu.ops.stencil import Topology

    e4 = Engine(seeds.empty((32, 256)), "B3/S23", mesh=m, topology=Topology.DEAD)
    row_strip = (wq // 4) * 4
    col_strip = (h // 2 + 2) * 4
    assert e4.halo_bytes_per_gen() == 2 * 4 * 1 * row_strip + 2 * 2 * 3 * col_strip

    # the optional field stays out of records when absent
    rec = StepMetrics(1, 1, 0.5, 1e6).to_dict()
    assert "halo_bytes" not in rec and "population" not in rec
    rec2 = StepMetrics(1, 1, 0.5, 1e6, halo_bytes=128).to_dict()
    assert rec2["halo_bytes"] == 128


def test_drop_shard_in_flight_detected_and_recovered(tmp_path):
    """The SURVEY §6 drop-a-shard-in-flight shape: one device buffer of a
    2D-mesh banded engine is zeroed at the device-shard level mid-run (no
    full-grid host round-trip), the damage is provably confined to that
    shard, an expected-population validator (redundant computation as the
    failure detector — SPMD determinism makes the clean trajectory exact)
    detects it at the next checkpoint boundary, and GuardedRun replays to
    the bit-exact clean trajectory."""
    import jax

    from gameoflifewithactors_tpu import Engine
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
    from gameoflifewithactors_tpu.utils import fault

    rng = np.random.default_rng(11)
    grid = rng.integers(0, 2, size=(64, 256), dtype=np.uint8)
    m = mesh_lib.make_mesh((2, 4), jax.devices())

    # clean trajectory: expected population at every checkpoint boundary
    ref = Engine(grid, "B3/S23", mesh=m, backend="pallas")
    expected_pop = {0: ref.population()}
    for gen in range(8, 41, 8):
        ref.step(8)
        expected_pop[gen] = ref.population()
    want = ref.snapshot()

    eng = Engine(grid, "B3/S23", mesh=m, backend="pallas")
    assert eng._banded
    guard = fault.GuardedRun(
        eng, checkpoint_every=8,
        checkpoint_path=str(tmp_path / "shard.npz"),
        validator=lambda e: e.population() == expected_pop[e.generation])
    guard.run(16)

    before = eng.snapshot()
    fault.drop_shard(eng, 3)                   # one band lost in flight
    after = eng.snapshot()
    diff_rows = np.flatnonzero((before != after).any(axis=1))
    assert diff_rows.size, "drop_shard must change the live state"
    # damage confined to ONE band: the (2,4) mesh flattens to 8 bands of
    # 8 rows; the zeroed rows all lie in a single 8-row slab, zeroed
    # full-width, and every other row is untouched
    band = diff_rows[0] // 8
    assert np.all(diff_rows // 8 == band)
    assert not after[band * 8:(band + 1) * 8].any()
    mask = np.ones(64, dtype=bool)
    mask[band * 8:(band + 1) * 8] = False
    np.testing.assert_array_equal(before[mask], after[mask])

    guard.run(24)                              # detector fires, replays
    assert guard.recoveries >= 1
    assert eng.generation == 40
    np.testing.assert_array_equal(eng.snapshot(), want)


def test_shard_injectors_refuse_invalid_targets():
    import jax

    from gameoflifewithactors_tpu import Engine
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
    from gameoflifewithactors_tpu.utils import fault

    unsharded = Engine(np.zeros((32, 64), dtype=np.uint8), "B3/S23")
    with pytest.raises(ValueError, match="sharded"):
        fault.drop_shard(unsharded, 0)

    m = mesh_lib.make_mesh((2, 4), jax.devices())
    rng = np.random.default_rng(0)
    sharded = Engine(rng.integers(0, 2, size=(64, 256), dtype=np.uint8),
                     "B3/S23", mesh=m, backend="packed")
    with pytest.raises(IndexError):
        fault.drop_shard(sharded, 99)
    # corrupt_shard on a packed binary engine scrambles exactly one shard
    pre = sharded.snapshot()
    fault.corrupt_shard(sharded, 1, seed=5)
    post = sharded.snapshot()
    assert (pre != post).any()
    sparse_eng = Engine(rng.integers(0, 2, size=(256, 256), dtype=np.uint8),
                        "B3/S23", mesh=m, backend="sparse")
    with pytest.raises(ValueError, match="sparse"):
        fault.drop_shard(sparse_eng, 0)


def test_guarded_run_recovers_banded_2d_mesh_engine(tmp_path):
    """Checkpoint-based recovery over the flattened-band kernel engine on
    a 2D mesh: a corrupted shard mid-run must roll back and replay to the
    exact uncorrupted trajectory — the fault story composed with the
    round-4 sharded path (checkpoint reload crosses the banded layout)."""
    import jax

    from gameoflifewithactors_tpu import Engine
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
    from gameoflifewithactors_tpu.utils import fault

    rng = np.random.default_rng(3)
    grid = rng.integers(0, 2, size=(64, 256), dtype=np.uint8)
    m = mesh_lib.make_mesh((2, 4), jax.devices())

    ref = Engine(grid, "B3/S23", mesh=m, backend="pallas")
    ref.step(32)
    want = ref.snapshot()

    eng = Engine(grid, "B3/S23", mesh=m, backend="pallas")
    assert eng._banded
    guard = fault.GuardedRun(
        eng, checkpoint_every=8,
        checkpoint_path=str(tmp_path / "band.npz"),
        validator=fault.population_bounds_validator(min_pop=1))
    guard.run(16)
    fault.drop_region(eng, 0, 0, 64, 256)      # lose everything: pop 0
    guard.run(16)                              # validator rejects, replays
    assert guard.recoveries >= 1
    np.testing.assert_array_equal(eng.snapshot(), want)


def test_render_multistate_ltl_snapshot(tmp_path):
    """The renderer and PPM export must accept C >= 3 LtL states from the
    plane engine's snapshot (states 0..C-1, like Generations)."""
    import io

    from gameoflifewithactors_tpu import Engine
    from gameoflifewithactors_tpu.coordinator import RenderFrame
    from gameoflifewithactors_tpu.utils import render

    rng = np.random.default_rng(7)
    grid = rng.integers(0, 4, size=(16, 32), dtype=np.uint8)
    e = Engine(grid, "R2,C4,M1,S3..8,B5..9")   # auto -> packed planes
    e.step(2)
    snap = e.snapshot()
    buf = io.StringIO()
    render.ConsoleRenderer(buf, ansi=False, charset="·█▓░")(RenderFrame(
        grid=snap, generation=e.generation, population=e.population(),
        full_shape=e.shape))
    lines = buf.getvalue().splitlines()
    assert len(lines) == 17 and "gen 2" in lines[-1]   # 16 rows + status
    path = tmp_path / "mltl.ppm"
    render.save_ppm(snap, path)
    data = path.read_bytes()
    assert data.startswith(b"P6") and len(data) > 16 * 32 * 3
