"""Sampling profiler (obs/profiler.py) + op-class perfetto attribution.

The claims under test (ISSUE 18 acceptance criteria):

- **classification** — slice names bucket into the five op classes with
  first-match ordering (a ``broadcast_multiply_fusion`` is compute, not
  a broadcast; an async collective-permute never reads as a copy);
- **parser extensions** — ``perfetto_summary`` handles the edge cases
  (empty trace, gzip vs plain byte-identical, nested/overlapping slices
  union-counted once) and on a synthetic TPU multi-track dump takes
  attribution from the busiest *classified* device track, never summing
  mirror layers;
- **measured overlap** — interval intersection of collective vs
  interior-compute unions across device tracks; ``None`` (absent, not
  0.0) on a host-only capture;
- **hard overhead budget** — a window/period ratio above 10% refuses to
  construct, and an armed-at-default run costs < 5% wall vs off;
- **byte-compat** — with the profiler off, RunReports carry no
  ``profile`` key at all;
- **COST discipline** — the fleet aggregator refuses to sum the
  per-chip profile gauges (``PerChipSumError``) while the
  device-seconds counter still sums.
"""

import contextlib
import gzip
import json
import time

import pytest

from gameoflifewithactors_tpu.obs import profiler as profiler_lib
from gameoflifewithactors_tpu.obs.aggregate import (
    PerChipSumError,
    sum_across_procs,
)
from gameoflifewithactors_tpu.obs.exporter import render_prometheus
from gameoflifewithactors_tpu.obs.profiler import (
    MAX_DUTY_CYCLE,
    OP_CLASSES,
    ProfileSampler,
    attribution_path_for,
    classify_slice,
)
from gameoflifewithactors_tpu.obs.registry import MetricsRegistry

# -- slice classification -----------------------------------------------------


@pytest.mark.parametrize("name,cls", [
    # collectives win over everything (async start/done markers included)
    ("collective-permute-start.1", "collective_permute"),
    ("collective_permute.2", "collective_permute"),
    ("all-reduce.3", "collective_permute"),
    ("ppermute", "collective_permute"),
    ("send.1", "collective_permute"),
    ("recv-done.4", "collective_permute"),
    # fusions/kernels before copy_reshape: this name contains "broadcast"
    # but is compute
    ("broadcast_multiply_fusion", "stencil"),
    ("fusion.12", "stencil"),
    ("conv_general_dilated", "stencil"),
    ("while.3", "stencil"),
    ("dot.7", "stencil"),
    ("goltpu.dispatch[cpu]", "stencil"),
    # bare data movement
    ("copy.4", "copy_reshape"),
    ("transpose.1", "copy_reshape"),
    ("bitcast.2", "copy_reshape"),
    # host/infeed traffic
    ("infeed.1", "infeed_host"),
    ("TransferToDevice", "infeed_host"),
    ("memcpyD2D", "infeed_host"),
    # no pattern: other
    ("ThunkExecutor::Execute", "other"),
    ("jit_run", "other"),
])
def test_classify_slice(name, cls):
    assert classify_slice(name) == cls


def test_attribution_path_rule():
    assert attribution_path_for("results/run.json") == \
        "results/run.attribution.json"
    assert attribution_path_for("run") == "run.attribution.json"


# -- perfetto_summary edge cases ----------------------------------------------


def _meta(pid, pname, threads):
    events = [{"ph": "M", "pid": pid, "name": "process_name",
               "args": {"name": pname}}]
    for tid, tname in threads.items():
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name", "args": {"name": tname}})
    return events


def _slice(pid, tid, ts, dur, name):
    return {"ph": "X", "pid": pid, "tid": tid, "ts": ts, "dur": dur,
            "name": name}


def _write_trace(path, events, gz=False):
    payload = json.dumps({"traceEvents": events})
    if gz:
        with gzip.open(str(path), "wt") as f:
            f.write(payload)
    else:
        path.write_text(payload)
    return str(path)


def test_perfetto_summary_empty_trace(tmp_path):
    from gameoflifewithactors_tpu.utils.profiling import perfetto_summary

    s = perfetto_summary(_write_trace(tmp_path / "t.json", []))
    assert s["tracks"] == [] and s["device_tracks"] == 0
    assert s["source"] is None and s["attribution_track"] is None
    assert s["op_class_us"] == {} and s["overlap"] is None


def test_perfetto_summary_gzip_and_plain_agree(tmp_path):
    from gameoflifewithactors_tpu.utils.profiling import perfetto_summary

    events = _meta(1, "/device:TPU:0", {1: "XLA Ops"}) + [
        _slice(1, 1, 0, 100, "fusion.1"),
        _slice(1, 1, 120, 30, "collective-permute.2"),
    ]
    plain = perfetto_summary(_write_trace(tmp_path / "t.json", events))
    gzipped = perfetto_summary(
        _write_trace(tmp_path / "t.json.gz", events, gz=True))
    assert plain == gzipped
    assert plain["source"] == "device_tracks"
    assert plain["op_class_us"] == {"stencil": 100.0,
                                    "collective_permute": 30.0}


def test_perfetto_summary_nested_and_overlapping_union(tmp_path):
    """Same-class slices that nest or overlap count their union once:
    two overlapping 100us fusions spanning [0, 150) are 150us of
    stencil, not 200."""
    from gameoflifewithactors_tpu.utils.profiling import perfetto_summary

    events = _meta(1, "/device:TPU:0", {1: "XLA Ops"}) + [
        _slice(1, 1, 0, 100, "fusion.1"),
        _slice(1, 1, 50, 100, "fusion.2"),
        _slice(1, 1, 60, 10, "fusion.nested"),
    ]
    s = perfetto_summary(_write_trace(tmp_path / "t.json", events))
    assert s["op_class_us"] == {"stencil": 150.0}
    assert s["device_busy_us"] == 150.0


def test_perfetto_summary_multi_track_attribution_not_summed(tmp_path):
    """A TPU dump mirrors one device across track layers. Attribution
    comes from the single track with the most *classified* busy time —
    the op layer beats a busier module-mirror layer whose slices all
    read ``other`` — and is never a sum across layers."""
    from gameoflifewithactors_tpu.utils.profiling import perfetto_summary

    events = (
        _meta(1, "/device:TPU:0", {1: "XLA Modules", 2: "XLA Ops"})
        + _meta(2, "/host:CPU", {9: "python"})
        + [
            # module layer: one big unclassifiable slice (100us, "other")
            _slice(1, 1, 0, 100, "jit_run.1"),
            # op layer: 80us of classified work
            _slice(1, 2, 0, 50, "fusion.1"),
            _slice(1, 2, 50, 30, "collective-permute.2"),
            # host is busiest in wall time but must not win attribution
            _slice(2, 9, 0, 500, "dispatch"),
        ])
    s = perfetto_summary(_write_trace(tmp_path / "t.json", events))
    assert s["source"] == "device_tracks"
    assert s["attribution_track"] == "device:TPU:0/XLA Ops"
    assert s["op_class_us"] == {"stencil": 50.0, "collective_permute": 30.0}
    # the busiest-track duty-cycle rule is unchanged: Modules layer wins
    # device_busy_us (100 > 80), and mirrors are never summed
    assert s["device_track"] == "device:TPU:0/XLA Modules"
    assert s["device_busy_us"] == 100.0


def test_perfetto_summary_measured_overlap_ratio(tmp_path):
    """collective [0,100) vs interior compute [50,150): half the
    collective time is hidden under compute -> ratio 0.5. Async
    collectives on their own track line still count (overlap is
    computed across ALL device tracks)."""
    from gameoflifewithactors_tpu.utils.profiling import perfetto_summary

    events = (
        _meta(1, "/device:TPU:0", {1: "XLA Ops", 2: "Async ops"})
        + [
            _slice(1, 2, 0, 100, "collective-permute-start.1"),
            _slice(1, 1, 50, 100, "fusion.interior"),
        ])
    s = perfetto_summary(_write_trace(tmp_path / "t.json", events))
    ov = s["overlap"]
    assert ov["collective_us"] == 100.0 and ov["compute_us"] == 100.0
    assert ov["overlapped_us"] == 50.0
    assert ov["ratio"] == pytest.approx(0.5)


def test_perfetto_summary_host_only_source(tmp_path):
    """A CPU capture has only host tracks: attribution still works
    (labeled host_tracks) but there is no overlap section to fabricate."""
    from gameoflifewithactors_tpu.utils.profiling import perfetto_summary

    events = _meta(2, "/host:CPU", {9: "python"}) + [
        _slice(2, 9, 0, 300, "broadcast_multiply_fusion"),
        _slice(2, 9, 300, 100, "copy.1"),
    ]
    s = perfetto_summary(_write_trace(tmp_path / "t.json", events))
    assert s["source"] == "host_tracks"
    assert s["device_tracks"] == 0 and s["overlap"] is None
    assert s["attribution_track"] == "host:CPU/python"
    assert s["op_class_us"] == {"stencil": 300.0, "copy_reshape": 100.0}


# -- ProfileSampler: folding, gauges, budget ----------------------------------


def _fake_summary(collective=100.0, stencil=300.0, overlapped=50.0,
                  source="device_tracks"):
    return {
        "source": source,
        "tracks": [{"track": "t", "busy_us": collective + stencil}],
        "op_class_us": {"collective_permute": collective, "stencil": stencil},
        "overlap": ({"collective_us": collective, "compute_us": stencil,
                     "overlapped_us": overlapped,
                     "ratio": overlapped / collective}
                    if source == "device_tracks" else None),
    }


def test_sampler_folds_windows_into_gauges_and_attribution():
    reg = MetricsRegistry()
    s = ProfileSampler(10.0, window_seconds=0.2, registry=reg,
                       capture=lambda w: _fake_summary())
    assert s.sample_once() is not None
    assert s.sample_once() is not None
    att = s.attribution()
    assert att["windows"] == 2 and att["capture_errors"] == 0
    assert att["source"] == "device_tracks" and att["per_chip"] is True
    assert att["op_class_seconds"]["collective_permute"] == \
        pytest.approx(200e-6)
    assert att["op_class_seconds"]["stencil"] == pytest.approx(600e-6)
    assert att["op_class_fraction"]["stencil"] == pytest.approx(0.75)
    assert set(att["op_class_seconds"]) == set(OP_CLASSES)
    assert att["halo_overlap_ratio_measured"] == pytest.approx(0.5)
    assert att["duty_cycle"] == pytest.approx(0.02)
    # the registry mirrors the cumulative view
    g = reg.gauge("profile_op_class_fraction", "")
    assert g.value(op_class="stencil", source="device_tracks") == \
        pytest.approx(0.75)
    c = reg.counter("profile_op_class_seconds_total", "")
    assert c.value(op_class="collective_permute", source="device_tracks") == \
        pytest.approx(200e-6)
    assert reg.gauge("halo_overlap_ratio_measured", "").value() == \
        pytest.approx(0.5)
    assert reg.gauge("profile_duty_cycle", "").value() == pytest.approx(0.02)
    assert reg.counter("profile_windows_total", "").value() == 2


def test_sampler_host_only_measured_overlap_is_absent_not_zero():
    reg = MetricsRegistry()
    s = ProfileSampler(10.0, registry=reg,
                       capture=lambda w: _fake_summary(source="host_tracks"))
    s.sample_once()
    att = s.attribution()
    assert att["source"] == "host_tracks"
    assert att["halo_overlap_ratio_measured"] is None
    assert "overlap_collective_seconds" not in att
    assert reg.gauge("halo_overlap_ratio_measured", "").value() is None


def test_sampler_static_gauge_cross_check():
    reg = MetricsRegistry()
    reg.gauge("halo_overlap_ratio", "static schedule").set(0.8)
    s = ProfileSampler(10.0, registry=reg,
                       capture=lambda w: _fake_summary(overlapped=60.0))
    s.sample_once()
    att = s.attribution()
    assert att["halo_overlap_ratio_static"] == pytest.approx(0.8)
    assert att["halo_overlap_ratio_measured"] == pytest.approx(0.6)
    assert att["overlap_measured_minus_static"] == pytest.approx(-0.2)


def test_sampler_capture_errors_never_raise():
    def boom(_w):
        raise RuntimeError("wedged backend")

    reg = MetricsRegistry()
    s = ProfileSampler(10.0, registry=reg, capture=boom)
    assert s.sample_once() is None
    att = s.attribution()
    assert att["windows"] == 0 and att["capture_errors"] == 1
    assert reg.counter("profile_capture_errors", "").value(
        error="RuntimeError") == 1


def test_sampler_refuses_budget_violation(monkeypatch):
    with pytest.raises(ValueError, match="overhead budget"):
        ProfileSampler(1.0, window_seconds=0.2)  # 20% > 10%
    with pytest.raises(ValueError, match="positive"):
        ProfileSampler(0.0)
    with pytest.raises(ValueError, match="positive"):
        ProfileSampler(10.0, window_seconds=-1)
    # at the budget boundary: exactly MAX_DUTY_CYCLE constructs
    s = ProfileSampler(2.0, window_seconds=2.0 * MAX_DUTY_CYCLE,
                       registry=MetricsRegistry(), capture=lambda w: None)
    assert s.window / s.period == pytest.approx(MAX_DUTY_CYCLE)
    # the env var is the default period
    monkeypatch.setenv(profiler_lib.ENV_SAMPLE, "5.5")
    s = ProfileSampler(registry=MetricsRegistry(), capture=lambda w: None)
    assert s.period == 5.5


def test_sampler_thread_captures_immediately_then_stops():
    reg = MetricsRegistry()
    s = ProfileSampler(3600.0, registry=reg, capture=lambda w: _fake_summary())
    with s:
        deadline = time.perf_counter() + 5.0
        while time.perf_counter() < deadline:
            if s.attribution()["windows"] >= 1:
                break
            time.sleep(0.01)
    # a run far shorter than one period still got its window
    assert s.attribution()["windows"] == 1


def test_arm_disarm_and_dispatch_annotation():
    assert profiler_lib.active_sampler() is None
    # unarmed: the annotation is free (a nullcontext, no jax import)
    ctx = profiler_lib.dispatch_annotation("goltpu.dispatch[test]")
    assert isinstance(ctx, contextlib.nullcontext)
    s = ProfileSampler(3600.0, registry=MetricsRegistry(),
                       capture=lambda w: None)
    try:
        assert profiler_lib.arm(s) is s
        assert profiler_lib.active_sampler() is s
        with profiler_lib.dispatch_annotation("goltpu.dispatch[test]"):
            pass
    finally:
        profiler_lib.disarm()
    assert profiler_lib.active_sampler() is None


# -- RunReport integration: byte-compat off, profile section on ---------------


def test_report_has_no_profile_key_when_off(tmp_path):
    from gameoflifewithactors_tpu.obs import compile as obs_compile
    from gameoflifewithactors_tpu.obs.report import RunReport, \
        build_run_report
    from gameoflifewithactors_tpu.obs.spans import SpanTracer

    rep = build_run_report(tracer=SpanTracer(),
                           compile_log=obs_compile.CompileEventLog(),
                           config={"off": True})
    d = rep.to_dict()
    assert "profile" not in d
    path = rep.save(str(tmp_path / "r.json"))
    assert "profile" not in json.loads(open(path).read())
    # and round-trips losslessly
    assert RunReport.load(path).to_dict() == d


def test_report_carries_profile_section_and_renders(tmp_path):
    from gameoflifewithactors_tpu.obs import compile as obs_compile
    from gameoflifewithactors_tpu.obs.report import RunReport, \
        build_run_report
    from gameoflifewithactors_tpu.obs.spans import SpanTracer

    reg = MetricsRegistry()
    s = ProfileSampler(10.0, registry=reg, capture=lambda w: _fake_summary())
    s.sample_once()
    rep = build_run_report(tracer=SpanTracer(),
                           compile_log=obs_compile.CompileEventLog(),
                           config={}, profile=s.attribution())
    d = rep.to_dict()
    assert d["profile"]["windows"] == 1
    back = RunReport.load(rep.save(str(tmp_path / "r.json")))
    assert back.profile == d["profile"]
    text = "\n".join(back.summary_lines())
    assert "sampling profiler" in text and "stencil" in text


# -- acceptance: overhead budget, armed vs off --------------------------------


def _workload():
    from gameoflifewithactors_tpu import Engine
    from gameoflifewithactors_tpu.models import seeds

    e = Engine(seeds.seeded((128, 128), "glider", 2, 2), "conway")
    e.step(60)
    e.population()  # force completion


def test_overhead_budget_armed_vs_off():
    """The <5% acceptance criterion: the same workload, profiler off vs
    armed at the default window with the minimum legal period, min of 3
    runs each (min-of-repeats is the standard noise-robust wall
    estimator; a small absolute epsilon absorbs CI scheduler jitter on
    a sub-second workload)."""
    _workload()  # warm the compile cache out of both measurements

    def best_of(n=3):
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            _workload()
            best = min(best, time.perf_counter() - t0)
        return best

    off = best_of()
    sampler = ProfileSampler(2.0, registry=MetricsRegistry())
    profiler_lib.arm(sampler)
    try:
        armed = best_of()
    finally:
        profiler_lib.disarm()
    assert armed <= off * 1.05 + 0.3, (off, armed)


# -- acceptance: CPU ghost run records static + measured overlap --------------


def test_ghost_run_report_records_both_overlap_fields(tmp_path):
    """One CPU ghost-pipeline run (2x2 mesh, gens_per_exchange=4) under
    armed telemetry: the RunReport's profile section carries the static
    schedule gauge AND the measured-overlap field — present as None on
    CPU (host tracks only), never a fabricated 0.0."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gameoflifewithactors_tpu.models.rules import CONWAY
    from gameoflifewithactors_tpu.obs.report import begin_run_telemetry
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.ops.stencil import Topology
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
    from gameoflifewithactors_tpu.parallel import sharded

    m = mesh_lib.make_mesh((2, 2), jax.devices()[:4])
    rng = np.random.default_rng(11)
    grid = rng.integers(0, 2, size=(64, 128), dtype=np.uint8)
    placed = mesh_lib.device_put_sharded_grid(
        bitpack.pack(jnp.asarray(grid)), m)

    telem = begin_run_telemetry(profile_sample=4.0)
    run = sharded.make_multi_step_packed_ghost(
        m, CONWAY, Topology.TORUS, gens_per_exchange=4)
    out = run(placed, 2)
    out.block_until_ready()
    rep = telem.finish(config={"mesh": [2, 2], "gens_per_exchange": 4})

    p = rep.profile
    assert p is not None and p["windows"] >= 1
    # the static schedule gauge rode along for the cross-check
    assert 0.0 < p["halo_overlap_ratio_static"] < 1.0
    # measured overlap: the key is present, and on CPU (no device
    # tracks) its value is None — absent, never 0.0
    assert "halo_overlap_ratio_measured" in p
    assert p["halo_overlap_ratio_measured"] is None
    # the artifact round-trips with the section intact
    saved = json.loads(open(rep.save(str(tmp_path / "ghost.json"))).read())
    assert saved["profile"]["halo_overlap_ratio_static"] == \
        p["halo_overlap_ratio_static"]


# -- COST discipline: the aggregator refuses per-chip profile gauges ----------


def test_aggregator_refuses_summing_profile_gauges():
    def expo(**series):
        reg = MetricsRegistry()
        for name, value in series.items():
            if name.endswith("_total"):
                reg.counter(name, "c").inc(value, op_class="stencil",
                                           source="device_tracks")
            else:
                reg.gauge(name, "g").set(value)
        return render_prometheus(reg.snapshot())

    per_proc = {}
    for i, ratio in enumerate((0.4, 0.6)):
        per_proc[f"w{i}"] = expo(
            halo_overlap_ratio_measured=ratio,
            profile_duty_cycle=0.02,
            profile_overhead_ratio=0.01,
            profile_op_class_seconds_total=1.5,
        )
    # per-chip ratios refuse the fleet sum — the honest view is per-proc
    for name in ("halo_overlap_ratio_measured", "profile_duty_cycle",
                 "profile_overhead_ratio"):
        with pytest.raises(PerChipSumError, match="per-chip"):
            sum_across_procs(per_proc, name)
    # the device-seconds counter is additive and sums fine
    assert sum_across_procs(
        per_proc, "profile_op_class_seconds_total") == pytest.approx(3.0)


def test_aggregator_refuses_profile_op_class_fraction():
    reg = MetricsRegistry()
    reg.gauge("profile_op_class_fraction", "g").set(
        0.7, op_class="stencil", source="device_tracks")
    per_proc = {"w0": render_prometheus(reg.snapshot())}
    with pytest.raises(PerChipSumError, match="per-chip"):
        sum_across_procs(per_proc, "profile_op_class_fraction")


def test_fleet_top_shows_profiler_duty_and_overhead():
    """scripts/fleet_top.py renders the armed-fleet visibility columns:
    PROF (duty cycle) and PROF-OH (measured overhead) from the profile
    gauges, '-' when unarmed or down."""
    import importlib.util
    import os

    from gameoflifewithactors_tpu.obs.aggregate import parse_exposition

    spec = importlib.util.spec_from_file_location(
        "fleet_top", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "fleet_top.py"))
    ft = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ft)

    assert ft.COLUMNS[-2:] == ("PROF", "PROF-OH")
    reg = MetricsRegistry()
    reg.gauge("profile_duty_cycle", "g").set(0.02)
    reg.gauge("profile_overhead_ratio", "g").set(0.013)
    row = ft.row_for("w0", parse_exposition(render_prometheus(reg.snapshot())))
    assert row[-2] == "2.0%" and row[-1] == "1.3%"
    # unarmed worker: dashes, never a fabricated zero
    unarmed = ft.row_for("w1", parse_exposition(_exposition_empty()))
    assert unarmed[-2] == "-" and unarmed[-1] == "-"
    # down worker: the whole row is dashes
    assert ft.row_for("w2", None)[-1] == "-"


def _exposition_empty():
    return render_prometheus(MetricsRegistry().snapshot())
