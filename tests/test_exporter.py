"""Prometheus exposition (obs/exporter.py): text-format golden rules +
the live HTTP endpoint.

The format test enforces promtool-style line grammar — every sample line
is ``name{labels} value`` with a legal metric name, HELP/TYPE comments
precede their samples, histogram ``le`` buckets are cumulative and end
at ``+Inf`` with ``_count``/``_sum`` — so any real scraper ingests the
output. No jax anywhere: the endpoint is the thing that must stay alive
when the device is wedged.
"""

import json
import re
import urllib.request

from gameoflifewithactors_tpu.obs.exporter import (
    CONTENT_TYPE,
    MetricsServer,
    render_prometheus,
)
from gameoflifewithactors_tpu.obs.registry import MetricsRegistry

# promtool-style line rules: metric name, optional {labels}, numeric value
_SAMPLE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r' -?[0-9.eE+\-]+(\.[0-9]+)?$')


def _demo_registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.counter("jit_compiles", "jit cache misses").inc(
        runner="multi_step_packed", kind="cache_miss")
    reg.counter("jit_compiles").inc(2, runner="multi_step_packed",
                                    kind="cache_hit")
    reg.gauge("hbm_bytes_in_use", "device memory currently allocated "
              "(bytes)").set(12345678, device="0", platform="tpu")
    h = reg.histogram("tick seconds", "per-tick wall", buckets=(0.01, 0.1))
    for v in (0.0078125, 0.0625, 0.5):  # binary-exact: the sum goldens
        h.observe(v, phase="step")
    return reg


def test_exposition_golden():
    text = render_prometheus(_demo_registry().snapshot())
    assert text == """\
# HELP goltpu_hbm_bytes_in_use device memory currently allocated (bytes)
# TYPE goltpu_hbm_bytes_in_use gauge
goltpu_hbm_bytes_in_use{device="0",platform="tpu"} 12345678
# HELP goltpu_jit_compiles jit cache misses
# TYPE goltpu_jit_compiles counter
goltpu_jit_compiles{kind="cache_miss",runner="multi_step_packed"} 1
goltpu_jit_compiles{kind="cache_hit",runner="multi_step_packed"} 2
# HELP goltpu_tick_seconds per-tick wall
# TYPE goltpu_tick_seconds histogram
goltpu_tick_seconds_bucket{phase="step",le="0.01"} 1
goltpu_tick_seconds_bucket{phase="step",le="0.1"} 2
goltpu_tick_seconds_bucket{phase="step",le="+Inf"} 3
goltpu_tick_seconds_sum{phase="step"} 0.5703125
goltpu_tick_seconds_count{phase="step"} 3
"""


def test_exposition_line_rules():
    """Every non-comment line scrapes: legal name, escaped labels,
    numeric value; HELP/TYPE precede samples; histogram buckets are
    cumulative through +Inf == _count."""
    reg = _demo_registry()
    # hostile names/labels must be sanitized/escaped, not emitted raw
    reg.counter("weird-metric.name", 'help with "quotes"\nand newline').inc(
        **{"label": 'va"l\nue'})
    text = render_prometheus(reg.snapshot())
    assert text.endswith("\n")
    seen_types = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(" ")
            seen_types[name] = mtype
            continue
        if line.startswith("#"):
            assert line.startswith("# HELP ")
            assert "\n" not in line  # escaped
            continue
        assert _SAMPLE.match(line), f"unscrapeable line: {line!r}"
    assert seen_types["goltpu_weird_metric_name"] == "counter"
    # cumulative le buckets: +Inf equals _count
    bucket_lines = [l for l in text.splitlines()
                    if l.startswith("goltpu_tick_seconds_bucket")]
    counts = [int(l.rsplit(" ", 1)[1]) for l in bucket_lines]
    assert counts == sorted(counts)
    inf = next(l for l in bucket_lines if 'le="+Inf"' in l)
    count = next(l for l in text.splitlines()
                 if l.startswith("goltpu_tick_seconds_count"))
    assert inf.rsplit(" ", 1)[1] == count.rsplit(" ", 1)[1]


def test_http_server_serves_and_updates():
    reg = _demo_registry()
    with MetricsServer(0, registry=reg, host="127.0.0.1") as srv:
        assert srv.port and srv.port > 0
        url = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(f"{url}/metrics", timeout=5) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == CONTENT_TYPE
            body = r.read().decode()
        assert 'goltpu_hbm_bytes_in_use{device="0",platform="tpu"} 12345678' \
            in body
        # live: a scrape AFTER a bump sees the new value (the endpoint
        # renders per request, it is not a startup snapshot)
        reg.gauge("hbm_bytes_in_use").set(999, device="0", platform="tpu")
        with urllib.request.urlopen(f"{url}/metrics", timeout=5) as r:
            assert 'platform="tpu"} 999' in r.read().decode()
        with urllib.request.urlopen(f"{url}/healthz", timeout=5) as r:
            assert json.loads(r.read())["ok"] is True
        try:
            urllib.request.urlopen(f"{url}/nope", timeout=5)
            assert False, "unknown path must 404"
        except urllib.error.HTTPError as exc:
            assert exc.code == 404
    assert srv.port is None  # stopped


def test_device_gauges_flow_through_exporter():
    """Sampler -> registry -> exposition: the acceptance-criteria path
    (goltpu_hbm_bytes_in_use visible to a scraper), against a fake
    memory_stats backend."""
    from gameoflifewithactors_tpu.obs.device import DeviceSampler

    reg = MetricsRegistry()
    fake = [{"device": "3", "platform": "tpu", "bytes_in_use": 2 ** 30,
             "peak_bytes_in_use": 2 ** 31, "bytes_limit": 16 * 2 ** 30}]
    DeviceSampler(registry=reg, backend=lambda: fake).sample_once()
    text = render_prometheus(reg.snapshot())
    assert 'goltpu_hbm_bytes_in_use{device="3",platform="tpu"} 1073741824' \
        in text
    assert 'goltpu_hbm_bytes_limit{device="3",platform="tpu"} 17179869184' \
        in text
    assert "goltpu_device_samples 1" in text


def test_healthz_info_hook_is_late_bound():
    """set_health_info installs/replaces the /healthz hook on a RUNNING
    server (the serve layer starts the exporter before the session
    service exists); the handler calls it per request, and a broken hook
    degrades to ok+info_error instead of killing the liveness probe."""
    reg = MetricsRegistry()
    counts = {"sessions": {"live": 1}, "lanes": 2}
    with MetricsServer(0, registry=reg, host="127.0.0.1") as srv:
        url = f"http://127.0.0.1:{srv.port}/healthz"
        with urllib.request.urlopen(url, timeout=5) as r:
            assert json.loads(r.read()) == {"ok": True}
        srv.set_health_info(lambda: counts)
        with urllib.request.urlopen(url, timeout=5) as r:
            got = json.loads(r.read())
        assert got["ok"] is True and got["lanes"] == 2
        counts["lanes"] = 7  # per-request call, not a startup snapshot
        with urllib.request.urlopen(url, timeout=5) as r:
            assert json.loads(r.read())["lanes"] == 7

        def boom() -> dict:
            raise RuntimeError("hook broke")

        srv.set_health_info(boom)
        with urllib.request.urlopen(url, timeout=5) as r:
            got = json.loads(r.read())
        assert got["ok"] is True and got["info_error"] is True


def test_histogram_custom_buckets_render_and_conflict():
    """Custom bucket boundaries (the admission queue-wait seconds, not
    the step-latency decades) reach the exposition; a later registration
    with CONFLICTING explicit buckets is a hard error, while buckets=None
    composes with whatever the instrument already has."""
    reg = MetricsRegistry()
    h = reg.histogram("queue_wait_seconds", "waits",
                      buckets=(0.5, 5.0, 300.0))
    h.observe(2.0, kind="q")
    text = render_prometheus(reg.snapshot())
    assert 'goltpu_queue_wait_seconds_bucket{kind="q",le="0.5"} 0' in text
    assert 'goltpu_queue_wait_seconds_bucket{kind="q",le="5"} 1' in text
    assert 'goltpu_queue_wait_seconds_bucket{kind="q",le="300"} 1' in text
    assert 'goltpu_queue_wait_seconds_bucket{kind="q",le="+Inf"} 1' in text
    assert 'goltpu_queue_wait_seconds_count{kind="q"} 1' in text
    assert reg.histogram("queue_wait_seconds") is h  # None = don't-care
    try:
        reg.histogram("queue_wait_seconds", buckets=(1.0, 2.0))
        raise AssertionError("conflicting buckets must be refused")
    except ValueError as exc:
        assert "buckets" in str(exc)
