"""The perf-regression gate: obs/diff.py + scripts/perf_gate.py.

Covers metric extraction from both artifact shapes, tolerance-band
classification in both directions, and the ISSUE-3 acceptance cases:
nonzero exit on a synthetically regressed report, and "skipped (stale)"
— never "ok" — for a needs_recapture record. The script runs as a
subprocess exactly as CI invokes it (stdlib-only, no package import).
"""

import copy
import json
import os
import subprocess
import sys

import pytest

from gameoflifewithactors_tpu.obs import diff as diff_lib

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_GATE = os.path.join(_REPO, "scripts", "perf_gate.py")


def _report(rate=1e9, wall=1.0, compile_s=2.0, tick_mean=0.1, stalls=0):
    return {
        "schema_version": 1,
        "step_metrics": [
            {"generation": 8, "generations_stepped": 8,
             "wall_seconds": wall, "cell_updates_per_sec": rate}],
        "compile_seconds_total": compile_s,
        "phase_seconds": {"coordinator.tick": {"total_s": tick_mean * 4,
                                               "count": 4,
                                               "mean_s": tick_mean}},
        "stalls": [{"label": f"tick{i}"} for i in range(stalls)],
    }


def _bench(value=2.2e12, **extra):
    return {"metric": "cell-updates/sec/chip, 16384x16384 B3/S23 "
                      "(pallas, 50% soup, tpu)",
            "value": value, "unit": "cell-updates/sec", **extra}


# -- extraction + classification ----------------------------------------------


def test_extract_metrics_both_shapes():
    m = diff_lib.extract_metrics(_report())
    assert m["step/best_cell_updates_per_sec"]["value"] == 1e9
    assert m["step/seconds_per_gen"]["value"] == 1.0 / 8
    assert m["compile/seconds_total"]["value"] == 2.0
    assert m["phase/coordinator.tick/mean_s"]["value"] == 0.1
    assert m["stalls/count"]["value"] == 0
    b = diff_lib.extract_metrics(_bench())
    assert b["bench/value"]["value"] == 2.2e12
    assert b["bench/value"]["direction"] == diff_lib.HIGHER
    assert diff_lib.extract_metrics({"weird": True}) == {}


def test_diff_within_tolerance_is_ok():
    rows = diff_lib.diff_records(_report(rate=1e9), _report(rate=0.9e9))
    by = {r.metric: r for r in rows}
    assert by["step/best_cell_updates_per_sec"].status == "ok"
    assert by["step/best_cell_updates_per_sec"].ratio == 0.9


def test_diff_flags_regression_and_improvement():
    rows = diff_lib.diff_records(_report(rate=1e9, tick_mean=0.1),
                                 _report(rate=0.5e9, tick_mean=0.05))
    by = {r.metric: r for r in rows}
    assert by["step/best_cell_updates_per_sec"].status == "regression"
    assert by["phase/coordinator.tick/mean_s"].status == "ok"  # 2x better
    rows2 = diff_lib.diff_records(_report(tick_mean=0.1),
                                  _report(tick_mean=0.5))
    assert {r.metric: r for r in rows2}[
        "phase/coordinator.tick/mean_s"].status == "regression"
    # regressions sort first so the table leads with what matters
    assert rows[0].status == "regression"


def test_sub_floor_timing_churn_is_not_a_regression():
    """A 5 µs -> 30 µs phase mean is scheduler noise: lower-is-better
    rows where both sides sit under the absolute floor report ok."""
    rows = diff_lib.diff_records(_report(tick_mean=5e-6),
                                 _report(tick_mean=3e-5))
    by = {r.metric: r for r in rows}
    assert by["phase/coordinator.tick/mean_s"].status == "ok"
    assert by["phase/coordinator.tick/mean_s"].ratio == pytest.approx(6.0)
    # the same 6x ratio ABOVE the floor is a real regression
    rows2 = diff_lib.diff_records(_report(tick_mean=0.05),
                                  _report(tick_mean=0.3))
    assert {r.metric: r for r in rows2}[
        "phase/coordinator.tick/mean_s"].status == "regression"


def test_any_new_stall_regresses():
    rows = diff_lib.diff_records(_report(stalls=0), _report(stalls=1))
    assert {r.metric: r for r in rows}["stalls/count"].status == "regression"


def test_missing_metrics_do_not_crash_the_diff():
    rows = diff_lib.diff_records(_report(), _bench())
    assert all(r.status == "missing" for r in rows)
    verdict = diff_lib.gate(_report(), _bench())
    assert verdict["status"] == "skipped"
    assert "no comparable" in verdict["reason"]


def test_gate_stale_is_skipped_never_ok():
    stale = _bench(value=1e12, needs_recapture=True,
                   stale=True, stale_reason="measured paths changed")
    fresh = _bench(value=2e12)
    # stale BASELINE: skipped even though current is faster
    assert diff_lib.gate(stale, fresh)["status"] == "skipped"
    # stale CURRENT: skipped even though it would regress
    v = diff_lib.gate(fresh, stale)
    assert v["status"] == "skipped" and "stale" in v["reason"]
    # same records unflagged: a real verdict
    assert diff_lib.gate(_bench(value=2e12),
                         _bench(value=1e12))["status"] == "regression"


def test_tolerance_overrides():
    assert diff_lib.tolerance_for("phase/engine.step/mean_s") == 0.60
    assert diff_lib.tolerance_for("bench/value") == 0.20
    assert diff_lib.tolerance_for("bench/value", {"bench/": 0.5}) == 0.5
    rows = diff_lib.diff_records(
        _bench(value=1e12), _bench(value=0.7e12),
        tolerances={"bench/": 0.5})
    assert rows[0].status == "ok"  # 30% drop inside the widened band


# -- the script, as CI runs it ------------------------------------------------


def _run_gate(tmp_path, baseline, current, *flags):
    bp, cp = tmp_path / "base.json", tmp_path / "cur.json"
    bp.write_text(json.dumps(baseline))
    cp.write_text(json.dumps(current))
    return subprocess.run(
        [sys.executable, _GATE, str(bp), str(cp), *flags],
        capture_output=True, text=True, cwd=_REPO)


def test_gate_script_ok_exit_zero(tmp_path):
    r = _run_gate(tmp_path, _report(rate=1e9), _report(rate=1.05e9))
    assert r.returncode == 0, r.stderr
    assert "perf gate: ok" in r.stdout


def test_gate_script_regression_exits_nonzero(tmp_path):
    regressed = _report(rate=0.4e9, tick_mean=0.5)
    r = _run_gate(tmp_path, _report(rate=1e9, tick_mean=0.1), regressed)
    assert r.returncode == 1
    assert "REGRESSION" in r.stdout
    # --informational reports the same verdict but never blocks
    r2 = _run_gate(tmp_path, _report(rate=1e9, tick_mean=0.1), regressed,
                   "--informational")
    assert r2.returncode == 0
    assert "REGRESSION" in r2.stdout


def test_gate_script_stale_reports_skipped(tmp_path):
    r = _run_gate(tmp_path, _bench(value=2e12),
                  _bench(value=2.1e12, needs_recapture=True))
    assert r.returncode == 0
    assert "skipped (stale)" in r.stdout
    assert "perf gate: ok" not in r.stdout


def test_gate_script_unwraps_bench_wrapper_and_json_mode(tmp_path):
    wrapper = {"n": 5, "cmd": "python bench.py", "rc": 0,
               "parsed": _bench(value=2e12)}
    r = _run_gate(tmp_path, wrapper, _bench(value=0.5e12), "--json")
    assert r.returncode == 1
    out = json.loads(r.stdout)
    assert out["status"] == "regression"
    assert out["rows"][0]["metric"] == "bench/value"


def test_gate_script_unusable_input_exits_two(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_report()))
    r = subprocess.run([sys.executable, _GATE, str(bad), str(ok)],
                       capture_output=True, text=True, cwd=_REPO)
    assert r.returncode == 2


def test_report_cli_diff_mode(tmp_path, capsys):
    from gameoflifewithactors_tpu import cli

    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(_report(rate=1e9)))
    b.write_text(json.dumps(_report(rate=0.5e9)))
    assert cli.main(["report", str(a), "--diff", str(b)]) == 0
    out = capsys.readouterr().out
    assert "step/best_cell_updates_per_sec" in out
    assert "REGRESSION" in out
    assert cli.main(["report", str(a), "--diff", str(b), "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert any(r["status"] == "regression" for r in rows)


def test_gate_provenance_recheck_via_module():
    """A commit-stamped bench record whose measured paths changed since
    capture is stale even without the PR-2 flags — record_staleness
    re-derives it from provenance."""
    rec = _bench(value=1e12, commit="0000000")  # commit not in this repo

    class FakeProv:
        @staticmethod
        def staleness(record):
            return {"stale": True, "reason": "cannot verify commit"}

    why = diff_lib.record_staleness(rec, provenance=FakeProv)
    assert why and "cannot verify" in why
    assert diff_lib.gate(rec, _bench(value=1e12),
                         provenance=FakeProv)["status"] == "skipped"
    # no provenance module supplied: the unstamped flags still decide
    assert diff_lib.record_staleness(rec) is None


def test_deep_copy_safety():
    """diff_records must not mutate its inputs (the CLI reuses them)."""
    base, cur = _report(), _report(rate=2e9)
    b0, c0 = copy.deepcopy(base), copy.deepcopy(cur)
    diff_lib.diff_records(base, cur)
    assert base == b0 and cur == c0


# -- attribution blame (ISSUE 18): advisory ranking, same exit contract -------


def _profiled(report, collective=0.1, stencil=0.5, windows=2, **classes):
    """Attach a sampler ``profile`` section: per-window seconds * window
    count, the cumulative shape ProfileSampler.attribution() emits."""
    op = {"collective_permute": collective * windows,
          "stencil": stencil * windows,
          "copy_reshape": 0.01 * windows,
          "infeed_host": 0.0, "other": 0.0}
    op.update({cls: v * windows for cls, v in classes.items()})
    report["profile"] = {"source": "device_tracks", "windows": windows,
                         "op_class_seconds": op}
    return report


def test_attribution_blame_ranks_largest_contribution_delta():
    """The acceptance sentence: "collective-permute +100%, stencil flat"
    — normalized per window (the two runs sampled different counts),
    largest busy-time delta first, a freshly-appeared class labeled new
    rather than divided by zero."""
    base = _profiled(_report(rate=1e9), windows=2)
    cur = _profiled(_report(rate=0.5e9), collective=0.2, windows=4,
                    infeed_host=0.05)
    rows = diff_lib.attribution_blame(base, cur)
    assert rows[0]["op_class"] == "collective_permute"
    assert rows[0]["delta_pct"] == pytest.approx(1.0)
    assert rows[0]["delta_s_per_window"] == pytest.approx(0.1)
    by = {r["op_class"]: r for r in rows}
    assert by["stencil"]["delta_pct"] == pytest.approx(0.0)      # flat
    assert by["infeed_host"]["delta_pct"] is None                # new
    text = "\n".join(diff_lib.format_blame(rows))
    assert "collective_permute" in text and "+100%" in text
    assert "flat" in text and "new" in text


def test_attribution_blame_empty_without_both_sides():
    assert diff_lib.attribution_blame(_report(), _report()) == []
    # one-sided attribution is not enough
    assert diff_lib.attribution_blame(_profiled(_report()), _report()) == []
    # an all-zero profile (sampler armed, nothing captured) is absent too
    zero = _report()
    zero["profile"] = {"windows": 1, "op_class_seconds":
                       {c: 0.0 for c in ("stencil", "other")}}
    assert diff_lib.attribution_blame(zero, _profiled(_report())) == []
    # bench records never carry attribution
    assert diff_lib.extract_attribution(_bench()) is None


def test_gate_verdict_blame_is_advisory():
    """Blame rides on the verdict when both sides carry attribution,
    and NEVER changes the status — the exit-code contract is pinned."""
    base = _profiled(_report(rate=1e9, tick_mean=0.1))
    bad = _profiled(_report(rate=0.5e9, tick_mean=0.5), collective=0.2)
    v = diff_lib.gate(base, bad)
    assert v["status"] == "regression"
    assert v["blame"][0]["op_class"] == "collective_permute"
    # same regression, no attribution: same status, no blame key
    v2 = diff_lib.gate(_report(rate=1e9, tick_mean=0.1),
                       _report(rate=0.5e9, tick_mean=0.5))
    assert v2["status"] == "regression" and "blame" not in v2
    # ok status with attribution: blame present, status untouched
    v3 = diff_lib.gate(base, _profiled(_report(rate=1.01e9, tick_mean=0.1)))
    assert v3["status"] == "ok"


def test_gate_script_blame_section_and_exit_contract(tmp_path):
    base = _profiled(_report(rate=1e9, tick_mean=0.1))
    bad = _profiled(_report(rate=0.5e9, tick_mean=0.5), collective=0.2)
    # regression with attribution: exit 1 (unchanged) + blame section
    r = _run_gate(tmp_path, base, bad)
    assert r.returncode == 1
    assert "attribution blame" in r.stdout
    assert "collective_permute" in r.stdout and "+100%" in r.stdout
    # ok run: exit 0, no blame section in the text output
    r2 = _run_gate(tmp_path, base, _profiled(_report(rate=1.02e9,
                                                     tick_mean=0.1)))
    assert r2.returncode == 0 and "attribution blame" not in r2.stdout
    # --json carries the machine-readable rows, exit still 1
    r3 = _run_gate(tmp_path, base, bad, "--json")
    assert r3.returncode == 1
    out = json.loads(r3.stdout)
    assert out["blame"][0]["op_class"] == "collective_permute"
    # a stale current record still skips with exit 0, attribution or not
    stale = dict(bad, needs_recapture=True)
    r4 = _run_gate(tmp_path, base, stale)
    assert r4.returncode == 0 and "skipped" in r4.stdout
