"""Activity-tiled sparse engine: exactness, sleep/wake, capacity fallback."""

import jax.numpy as jnp
import numpy as np
import pytest

from gameoflifewithactors_tpu.models import seeds
from gameoflifewithactors_tpu.models.rules import CONWAY
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.packed import multi_step_packed
from gameoflifewithactors_tpu.ops.sparse import SparseEngineState
from gameoflifewithactors_tpu.ops.stencil import Topology


def _dense_reference(grid, n):
    p = bitpack.pack(jnp.asarray(grid))
    return np.asarray(
        bitpack.unpack(multi_step_packed(p, n, rule=CONWAY, topology=Topology.DEAD))
    )


def _sparse(grid, n, **kw):
    s = SparseEngineState(bitpack.pack(jnp.asarray(grid)), CONWAY, **kw)
    s.step(n)
    return np.asarray(bitpack.unpack(s.packed)), s


def test_sparse_matches_dense_glider():
    g = seeds.seeded((128, 256), "glider", 4, 4)
    got, s = _sparse(g, 40, tile_rows=16, tile_words=2, capacity=16)
    np.testing.assert_array_equal(got, _dense_reference(g, 40))
    # a lone glider keeps only a handful of tiles awake
    assert s.active_tiles() <= 4


def test_sparse_still_life_sleeps():
    g = seeds.seeded((64, 128), "block", 16, 32)
    got, s = _sparse(g, 5, tile_rows=16, tile_words=1, capacity=8)
    np.testing.assert_array_equal(got, _dense_reference(g, 5))
    assert s.active_tiles() == 0  # still life: everything asleep


def test_sparse_gun_matches_dense():
    g = seeds.seeded((128, 256), "gosper_gun", 8, 8)
    got, s = _sparse(g, 60, tile_rows=16, tile_words=2, capacity=32)
    np.testing.assert_array_equal(got, _dense_reference(g, 60))
    assert got.sum() == 36 + 2 * 5  # gun + 2 gliders at gen 60


def test_sparse_capacity_overflow_falls_back_dense():
    rng = np.random.default_rng(0)
    g = rng.integers(0, 2, size=(64, 128), dtype=np.uint8)  # soup: all tiles hot
    got, s = _sparse(g, 6, tile_rows=16, tile_words=1, capacity=2)
    np.testing.assert_array_equal(got, _dense_reference(g, 6))


def test_sparse_wake_across_tiles():
    """A glider leaving its tile must wake the next tile (dilation)."""
    g = seeds.seeded((96, 128), "glider", 1, 1)
    got, s = _sparse(g, 90, tile_rows=16, tile_words=1, capacity=16)
    np.testing.assert_array_equal(got, _dense_reference(g, 90))
    assert got.sum() == 5  # glider survived three tile crossings


def test_sparse_tile_divisibility_validated():
    with pytest.raises(ValueError):
        SparseEngineState(jnp.zeros((30, 4), jnp.uint32), CONWAY,
                          tile_rows=16, tile_words=1)


def test_engine_sparse_backend():
    from gameoflifewithactors_tpu import Engine

    g = seeds.seeded((128, 128), "glider", 4, 4)
    e = Engine(g, "conway", backend="sparse", topology=Topology.DEAD)
    e.step(40)
    np.testing.assert_array_equal(e.snapshot(), _dense_reference(g, 40))
    assert e.population() == 5
    with pytest.raises(ValueError, match="DEAD"):
        Engine(g, "conway", backend="sparse")  # default torus rejected


def test_sparse_rejects_b0_rules():
    from gameoflifewithactors_tpu.models.rules import parse_rule

    with pytest.raises(ValueError, match="B0"):
        SparseEngineState(jnp.zeros((32, 4), jnp.uint32), parse_rule("B0/S8"))


def test_engine_sparse_opts_and_cell_unit_errors():
    from gameoflifewithactors_tpu import Engine

    g = seeds.seeded((64, 128), "glider", 4, 4)  # needs non-default tiling
    e = Engine(g, "conway", backend="sparse", topology=Topology.DEAD,
               sparse_opts=dict(tile_rows=16, tile_words=1, capacity=16))
    e.step(4)
    assert e.population() == 5
    assert e._state is None  # no dead second copy of the grid
    with pytest.raises(ValueError, match=r"64, 64"):
        Engine(np.zeros((64, 64), np.uint8), "conway", backend="sparse",
               topology=Topology.DEAD)
