"""Activity-tiled sparse engine: exactness, sleep/wake, capacity fallback."""

import jax.numpy as jnp
import numpy as np
import pytest

from gameoflifewithactors_tpu.models import seeds
from gameoflifewithactors_tpu.models.rules import CONWAY
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.packed import multi_step_packed
from gameoflifewithactors_tpu.ops.sparse import SparseEngineState
from gameoflifewithactors_tpu.ops.stencil import Topology


def _dense_reference(grid, n):
    p = bitpack.pack(jnp.asarray(grid))
    return np.asarray(
        bitpack.unpack(multi_step_packed(p, n, rule=CONWAY, topology=Topology.DEAD))
    )


def _sparse(grid, n, **kw):
    s = SparseEngineState(bitpack.pack(jnp.asarray(grid)), CONWAY, **kw)
    s.step(n)
    return np.asarray(bitpack.unpack(s.packed)), s


def test_sparse_matches_dense_glider():
    g = seeds.seeded((128, 256), "glider", 4, 4)
    got, s = _sparse(g, 40, tile_rows=16, tile_words=2, capacity=16)
    np.testing.assert_array_equal(got, _dense_reference(g, 40))
    # a lone glider keeps only a handful of tiles awake
    assert s.active_tiles() <= 4


def test_sparse_still_life_sleeps():
    g = seeds.seeded((64, 128), "block", 16, 32)
    got, s = _sparse(g, 5, tile_rows=16, tile_words=1, capacity=8)
    np.testing.assert_array_equal(got, _dense_reference(g, 5))
    assert s.active_tiles() == 0  # still life: everything asleep


def test_sparse_gun_matches_dense():
    g = seeds.seeded((128, 256), "gosper_gun", 8, 8)
    got, s = _sparse(g, 60, tile_rows=16, tile_words=2, capacity=32)
    np.testing.assert_array_equal(got, _dense_reference(g, 60))
    assert got.sum() == 36 + 2 * 5  # gun + 2 gliders at gen 60


def test_sparse_capacity_overflow_falls_back_dense():
    rng = np.random.default_rng(0)
    g = rng.integers(0, 2, size=(64, 128), dtype=np.uint8)  # soup: all tiles hot
    got, s = _sparse(g, 6, tile_rows=16, tile_words=1, capacity=2)
    np.testing.assert_array_equal(got, _dense_reference(g, 6))


def test_sparse_wake_across_tiles():
    """A glider leaving its tile must wake the next tile (dilation)."""
    g = seeds.seeded((96, 128), "glider", 1, 1)
    got, s = _sparse(g, 90, tile_rows=16, tile_words=1, capacity=16)
    np.testing.assert_array_equal(got, _dense_reference(g, 90))
    assert got.sum() == 5  # glider survived three tile crossings


def test_sparse_tile_divisibility_validated():
    with pytest.raises(ValueError):
        SparseEngineState(jnp.zeros((30, 4), jnp.uint32), CONWAY,
                          tile_rows=16, tile_words=1)


def test_engine_sparse_backend():
    from gameoflifewithactors_tpu import Engine

    g = seeds.seeded((128, 128), "glider", 4, 4)
    e = Engine(g, "conway", backend="sparse", topology=Topology.DEAD)
    e.step(40)
    np.testing.assert_array_equal(e.snapshot(), _dense_reference(g, 40))
    assert e.population() == 5
    # default torus is supported too (ring refresh); glider wraps the seam
    et = Engine(g, "conway", backend="sparse")
    et.step(40)
    want = bitpack.unpack(multi_step_packed(
        bitpack.pack(jnp.asarray(g)), 40, rule=CONWAY, topology=Topology.TORUS))
    np.testing.assert_array_equal(et.snapshot(), np.asarray(want))


def test_sparse_rejects_b0_rules():
    from gameoflifewithactors_tpu.models.rules import parse_rule

    with pytest.raises(ValueError, match="B0"):
        SparseEngineState(jnp.zeros((32, 4), jnp.uint32), parse_rule("B0/S8"))


def test_engine_sparse_opts_and_cell_unit_errors():
    from gameoflifewithactors_tpu import Engine

    g = seeds.seeded((64, 128), "glider", 4, 4)  # needs non-default tiling
    e = Engine(g, "conway", backend="sparse", topology=Topology.DEAD,
               sparse_opts=dict(tile_rows=16, tile_words=1, capacity=16))
    e.step(4)
    assert e.population() == 5
    assert e._state is None  # no dead second copy of the grid
    # no explicit opts: auto_tile adapts to the narrow grid (width 64 =
    # 2 packed words -> 2-word tiles) instead of failing on the defaults
    e2 = Engine(np.zeros((64, 64), np.uint8), "conway", backend="sparse",
                topology=Topology.DEAD)
    assert e2._sparse.tile_words == 2
    # explicitly indivisible opts still fail with a cell-unit message
    with pytest.raises(ValueError, match=r"64, 64"):
        Engine(np.zeros((64, 64), np.uint8), "conway", backend="sparse",
               topology=Topology.DEAD, sparse_opts=dict(tile_words=4))


# -- sharded sparse: per-device activity skipping -----------------------------

class TestShardedSparse:
    def _mesh(self, shape=(2, 4)):
        import jax

        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        return mesh_lib.make_mesh(shape, jax.devices()[: shape[0] * shape[1]])

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    def test_bit_identity_random_soup(self, topology):
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.ops.packed import multi_step_packed
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
        from gameoflifewithactors_tpu.parallel import sharded

        m = self._mesh()
        rng = np.random.default_rng(9)
        g = rng.integers(0, 2, size=(64, 128), dtype=np.uint8)
        want = np.asarray(bitpack.unpack(multi_step_packed(
            bitpack.pack(jnp.asarray(g)), 20, rule=CONWAY, topology=topology)))
        p = mesh_lib.device_put_sharded_grid(bitpack.pack(jnp.asarray(g)), m)
        run = sharded.make_multi_step_packed_sparse(m, CONWAY, topology)
        out, _ = run(p, sharded.initial_flags(m), 20)
        np.testing.assert_array_equal(np.asarray(bitpack.unpack(out)), want)

    def test_still_life_puts_all_tiles_to_sleep(self):
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models import seeds
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
        from gameoflifewithactors_tpu.parallel import sharded

        m = self._mesh()
        g = seeds.seeded((64, 128), "block", 10, 10)
        p = mesh_lib.device_put_sharded_grid(bitpack.pack(jnp.asarray(g)), m)
        run = sharded.make_multi_step_packed_sparse(m, CONWAY, Topology.TORUS)
        out, flags = run(p, sharded.initial_flags(m), 3)
        assert np.asarray(flags).sum() == 0, "block is a still life; all asleep"
        out2, flags2 = run(out, flags, 50)  # sleeping universe stays exact
        np.testing.assert_array_equal(np.asarray(bitpack.unpack(out2)), g)
        assert np.asarray(flags2).sum() == 0

    def test_glider_wakes_tiles_as_it_travels(self):
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models import seeds
        from gameoflifewithactors_tpu.ops.packed import multi_step_packed
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
        from gameoflifewithactors_tpu.parallel import sharded

        m = self._mesh((2, 2))
        g = seeds.seeded((64, 64), "glider", 1, 1)  # NW tile only
        p = mesh_lib.device_put_sharded_grid(bitpack.pack(jnp.asarray(g)), m)
        run = sharded.make_multi_step_packed_sparse(m, CONWAY, Topology.TORUS)
        out, flags = run(p, sharded.initial_flags(m), 4)
        f = np.asarray(flags)
        assert f[0, 0] == 1, "tile carrying the glider stays awake"
        # after ~100 gens the glider has crossed into other tiles; full
        # trajectory must match the dense engine exactly
        out, flags = run(out, flags, 116)
        want = np.asarray(bitpack.unpack(multi_step_packed(
            bitpack.pack(jnp.asarray(g)), 120, rule=CONWAY, topology=Topology.TORUS)))
        np.testing.assert_array_equal(np.asarray(bitpack.unpack(out)), want)

    def test_engine_routes_sparse_with_mesh(self):
        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.models import seeds

        m = self._mesh()
        e = Engine(seeds.seeded((64, 128), "blinker", 30, 60), "B3/S23",
                   mesh=m, backend="sparse")
        e.step(2)
        assert e.population() == 3
        np.testing.assert_array_equal(
            e.snapshot(), seeds.seeded((64, 128), "blinker", 30, 60))
        # torus + mesh + sparse is allowed (single-device sparse is DEAD-only)
        e2 = Engine(seeds.empty((64, 128)), "B3/S23", mesh=m,
                    backend="sparse", topology=Topology.TORUS)
        e2.step(5)
        assert e2.population() == 0


# -- tiled sharded sparse: per-tile skipping INSIDE each shard ----------------

class TestTiledShardedSparse:
    """make_multi_step_packed_sparse_tiled (VERDICT round-2 item #5): the
    single-device activity tiling composed within each device's shard, so
    a mostly-empty sharded universe sleeps at tile granularity."""

    def _mesh(self, shape=(2, 4)):
        import jax

        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        return mesh_lib.make_mesh(shape, jax.devices()[: shape[0] * shape[1]])

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    @pytest.mark.parametrize("mesh_shape", [(2, 4), (8, 1), (2, 2)])
    def test_bit_identity_gosper_gun(self, mesh_shape, topology):
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models import seeds
        from gameoflifewithactors_tpu.ops.packed import multi_step_packed
        from gameoflifewithactors_tpu.ops.sparse import auto_tile
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
        from gameoflifewithactors_tpu.parallel import sharded

        m = self._mesh(mesh_shape)
        H, W = 128, 512
        g = seeds.seeded((H, W), "gosper_gun", 40, 100)
        p = bitpack.pack(jnp.asarray(g))
        tr, tw = auto_tile(H // mesh_shape[0], (W // 32) // mesh_shape[1])
        run = sharded.make_multi_step_packed_sparse_tiled(
            m, CONWAY, topology, tile_rows=tr, tile_words=tw)
        act = sharded.initial_tile_activity(p, m, tr, tw)
        out, act = run(mesh_lib.device_put_sharded_grid(p, m), act, 64)
        want = multi_step_packed(p, 64, rule=CONWAY, topology=topology)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(want))
        # config-#5 shape: work stays proportional to active tiles — the
        # gun + emitted gliders occupy a small corner of the tile map
        f = np.asarray(act)
        assert 0 < f.sum() <= f.size // 4, (f.sum(), f.size)

    def test_still_life_sleeps_per_tile_not_per_device(self):
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models import seeds
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
        from gameoflifewithactors_tpu.parallel import sharded

        m = self._mesh((2, 2))
        g = np.asarray(seeds.seeded((128, 256), "block", 10, 10))
        # one blinker on ANOTHER device's shard: that device has exactly
        # one awake tile while its other tiles (and the block's device
        # after settling) sleep
        g |= np.asarray(seeds.seeded((128, 256), "blinker", 100, 200))
        p = bitpack.pack(jnp.asarray(g))
        run = sharded.make_multi_step_packed_sparse_tiled(
            m, CONWAY, Topology.TORUS, tile_rows=16, tile_words=2)
        act = sharded.initial_tile_activity(p, m, 16, 2)
        out, act = run(mesh_lib.device_put_sharded_grid(p, m), act, 4)
        f = np.asarray(act)
        assert f.sum() == 1, f"only the blinker tile stays awake, got {f.sum()}"
        out2, act2 = run(out, act, 50)
        # the block region is bit-exact after 54 gens of mostly-sleeping run
        np.testing.assert_array_equal(
            np.asarray(bitpack.unpack(out2))[:64, :128], g[:64, :128])

    def test_capacity_overflow_takes_dense_branch_exactly(self):
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.ops.packed import multi_step_packed
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
        from gameoflifewithactors_tpu.parallel import sharded

        m = self._mesh((2, 2))
        rng = np.random.default_rng(17)
        g = rng.integers(0, 2, size=(64, 128), dtype=np.uint8)  # 50% soup
        p = bitpack.pack(jnp.asarray(g))
        # capacity 2 << active tiles: every device overflows into the
        # dense branch every generation; results must stay bit-exact
        run = sharded.make_multi_step_packed_sparse_tiled(
            m, CONWAY, Topology.TORUS, tile_rows=8, tile_words=1, capacity=2)
        act = sharded.initial_tile_activity(p, m, 8, 1)
        out, _ = run(mesh_lib.device_put_sharded_grid(p, m), act, 12)
        want = multi_step_packed(p, 12, rule=CONWAY, topology=Topology.TORUS)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(want))

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    def test_generations_tiled_bit_identity(self, topology):
        """Plane-stack twin: a decaying Brain blob over the tile map."""
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.packed_generations import (
            multi_step_packed_generations,
            pack_generations_for,
        )
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
        from gameoflifewithactors_tpu.parallel import sharded

        rule = parse_any("brain")
        m = self._mesh((2, 4))
        grid = np.zeros((64, 256), np.uint8)
        grid[20:24, 60:66] = 2
        grid[21, 61] = 1
        planes = pack_generations_for(jnp.asarray(grid), rule)
        want = np.asarray(multi_step_packed_generations(
            planes, 24, rule=rule, topology=topology))
        run = sharded.make_multi_step_generations_packed_sparse_tiled(
            m, rule, topology, tile_rows=16, tile_words=1)
        act = sharded.initial_tile_activity(planes, m, 16, 1)
        out, act = run(mesh_lib.device_put_sharded_grid(planes, m), act, 24)
        np.testing.assert_array_equal(np.asarray(out), want)
        # the map stays sparse; under DEAD the blob may burn out entirely
        # (everything asleep) — under TORUS something survives the wrap
        f = np.asarray(act)
        assert f.sum() <= f.size // 2

    def test_b0_rule_rejected(self):
        from gameoflifewithactors_tpu.models.rules import parse_rule
        from gameoflifewithactors_tpu.parallel import sharded

        with pytest.raises(ValueError, match="B0"):
            sharded.make_multi_step_packed_sparse_tiled(
                self._mesh((2, 2)), parse_rule("B0/S8"), Topology.TORUS,
                tile_rows=8, tile_words=1)

    def test_engine_facade_tiled_sparse(self):
        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.models import seeds

        m = self._mesh((2, 2))
        grid = np.asarray(seeds.seeded((128, 256), "gosper_gun", 30, 60))
        e = Engine(grid, "conway", mesh=m, backend="sparse",
                   topology=Topology.DEAD)
        ref = Engine(grid, "conway", topology=Topology.DEAD)
        e.step(40)
        ref.step(40)
        np.testing.assert_array_equal(e.snapshot(), ref.snapshot())
        assert e._sparse_tiles is not None           # tiled path engaged
        assert e.halo_bytes_per_gen() > 0            # flag map accounted
        # set_grid re-seeds the tile map from the new grid's live tiles
        e.set_grid(np.zeros((128, 256), np.uint8))
        assert int(np.asarray(e._flags).sum()) == 0
        e.step(3)
        assert e.population() == 0

    def test_set_grid_wakes_sleeping_tiles(self):
        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.models import seeds

        m = self._mesh()
        e = Engine(seeds.empty((64, 128)), "B3/S23", mesh=m, backend="sparse")
        e.step(3)  # empty universe: everything asleep
        assert np.asarray(e._flags).sum() == 0
        e.set_grid(seeds.seeded((64, 128), "blinker", 30, 60))
        e.step(2)  # must compute again, not stay asleep
        assert e.population() == 3

    def test_mesh_sparse_opts_apply_and_flag_halo_counted(self):
        import warnings as w

        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.models import seeds

        m = self._mesh()
        # both sharded sparse layouts honor sparse_opts now (tiled paths):
        # no "ignored" warning on either
        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            e = Engine(seeds.empty((64, 128)), "B3/S23", mesh=m,
                       backend="sparse", sparse_opts={"capacity": 99})
            Engine(seeds.empty((64, 128)), "brain", mesh=m,
                   backend="sparse", sparse_opts={"capacity": 99})
        assert not any("ignores them" in str(c.message) for c in caught)
        # flag-map halo rides on top of the grid halo in the estimate:
        # 64x128 over (2, 4) auto-tiles to a (1, 1) local map, so the
        # strips match the per-device-flag constants (4 B rows, 12 B cols)
        plain = Engine(seeds.empty((64, 128)), "B3/S23", mesh=m, backend="packed")
        row_sends, col_sends = 2 * 4 * 2, 2 * 2 * 4
        assert (e.halo_bytes_per_gen() - plain.halo_bytes_per_gen()
                == row_sends * 4 + col_sends * 12)


def test_sparse_at_scale_8192():
    """VERDICT round-1 Missing #4: config #5's shape exercised at >=8192².

    Word-aligned small-patch seeding (as scripts/config5_sparse.py does at
    65536²), 64 generations, bit-identity against the dense packed step on
    the full 8192² grid, and the sparse invariant: compute stayed ∝ the
    gun's footprint (a handful of active tiles out of 16k), not the grid.
    """
    side = 8192
    words = side // 32
    grid = seeds.seeded_packed((side, side), "gosper_gun",
                               top=side // 2, left_word=words // 2)

    s = SparseEngineState(jnp.asarray(grid), CONWAY)
    s.step(64)
    want = multi_step_packed(jnp.asarray(grid), 64, rule=CONWAY,
                             topology=Topology.DEAD)
    np.testing.assert_array_equal(np.asarray(s.packed), np.asarray(want))
    assert s.active_tiles() <= 8
    assert s.active_tiles() < (side // s.tile_rows) * (words // s.tile_words) // 1000


def _torus_reference(grid, n):
    p = bitpack.pack(jnp.asarray(grid))
    return np.asarray(
        bitpack.unpack(multi_step_packed(p, n, rule=CONWAY, topology=Topology.TORUS))
    )


def _sparse_torus(grid, n, **kw):
    s = SparseEngineState(bitpack.pack(jnp.asarray(grid)), CONWAY,
                          topology=Topology.TORUS, **kw)
    s.step(n)
    return np.asarray(bitpack.unpack(s.packed)), s


@pytest.mark.parametrize("top,left", [(2, 118), (58, 4), (58, 118), (2, 4)],
                         ids=["east-seam", "south-seam", "corner", "interior"])
def test_sparse_torus_glider_crosses_seams(top, left):
    """The glider must wrap every seam bit-identically to the packed torus
    step, and the sparse invariant must hold: the traveling ship keeps only
    a few tiles awake while crossing."""
    g = seeds.seeded((64, 128), "glider", top, left)
    for gens in (16, 64, 180):
        got, s = _sparse_torus(g, gens, tile_rows=16, tile_words=1, capacity=24)
        np.testing.assert_array_equal(got, _torus_reference(g, gens),
                                      err_msg=f"gens={gens}")
        assert s.active_tiles() <= 6


def test_sparse_torus_still_life_sleeps_on_seam():
    # a block straddling the corner seam is a still life ON THE TORUS —
    # after one generation everything must fall asleep
    g = np.zeros((64, 128), dtype=np.uint8)
    g[0, 0] = g[0, -1] = g[-1, 0] = g[-1, -1] = 1  # 2x2 block across corners
    got, s = _sparse_torus(g, 8, tile_rows=16, tile_words=1, capacity=24)
    np.testing.assert_array_equal(got, _torus_reference(g, 8))
    assert s.active_tiles() == 0


def test_sparse_torus_capacity_overflow_dense_fallback():
    rng = np.random.default_rng(1)
    g = rng.integers(0, 2, size=(64, 128), dtype=np.uint8)  # everything awake
    got, _ = _sparse_torus(g, 12, tile_rows=16, tile_words=1, capacity=4)
    np.testing.assert_array_equal(got, _torus_reference(g, 12))


def test_auto_tile_defaults_and_scaling():
    from gameoflifewithactors_tpu.ops.sparse import MAX_MAP_ENTRIES, auto_tile

    # small grids keep the defaults
    assert auto_tile(1024, 32) == (32, 4)
    # 65536^2 packed is (65536, 2048): the map must shrink to <= 2^16
    tr, tw = auto_tile(65536, 2048)
    assert (65536 // tr) * (2048 // tw) <= MAX_MAP_ENTRIES
    assert 65536 % tr == 0 and 2048 % tw == 0
    # indivisible shapes degrade but never violate divisibility
    tr, tw = auto_tile(96, 6)
    assert 96 % tr == 0 and 6 % tw == 0


def test_sparse_auto_tiles_match_explicit_tiles():
    # same universe stepped with auto-chosen vs default tiles: identical
    rng = np.random.default_rng(5)
    g = np.zeros((256, 256), np.uint8)
    g[100:140, 60:200] = rng.integers(0, 2, (40, 140), np.uint8)
    p = jnp.asarray(bitpack.pack(jnp.asarray(g)))
    a = SparseEngineState(p, CONWAY, topology=Topology.TORUS)
    b = SparseEngineState(p, CONWAY, tile_rows=64, tile_words=8,
                          topology=Topology.TORUS)
    a.step(48)
    b.step(48)
    np.testing.assert_array_equal(np.asarray(a.packed), np.asarray(b.packed))


def test_adaptive_capacity_starts_small_and_escalates():
    # a small still patch: adaptive capacity starts near the activity...
    g = np.zeros((512, 512), np.uint8)
    g[100:103, 100:110] = 1
    p = jnp.asarray(bitpack.pack(jnp.asarray(g)))
    s = SparseEngineState(p, CONWAY, topology=Topology.DEAD)
    assert s._adaptive and s.capacity <= 64
    # ...then a capacity-busting soup forces doubling, never a wrong result
    soup = np.random.default_rng(3).integers(0, 2, (512, 512), np.uint8)
    p2 = jnp.asarray(bitpack.pack(jnp.asarray(soup)))
    s2 = SparseEngineState(p2, CONWAY, topology=Topology.DEAD)
    s2._set_capacity(32)  # simulate a badly-undersized start
    s2.step(24)
    want = bitpack.unpack(multi_step_packed(
        p2, 24, rule=CONWAY, topology=Topology.DEAD))
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack(s2.packed)), np.asarray(want))
    assert s2.capacity > 32  # escalated rather than dense-stepping forever
    # and never beyond the number of tiles that exist (64 here)
    assert s2.capacity <= 64
    # dense-ish seeds clamp at construction too, instead of batching
    # hundreds of fill windows forever
    s3 = SparseEngineState(p2, CONWAY, topology=Topology.DEAD)
    assert s3.capacity <= 64


def test_explicit_capacity_stays_fixed():
    soup = np.random.default_rng(4).integers(0, 2, (256, 256), np.uint8)
    p = jnp.asarray(bitpack.pack(jnp.asarray(soup)))
    s = SparseEngineState(p, CONWAY, capacity=16, topology=Topology.DEAD)
    s.step(12)
    assert s.capacity == 16  # dense fallback, no silent escalation
    want = bitpack.unpack(multi_step_packed(
        p, 12, rule=CONWAY, topology=Topology.DEAD))
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack(s.packed)), np.asarray(want))


class TestSparseGenerations:
    """Activity-tiled stepping for the multi-state family: the (b, H, W/32)
    plane stack rides the same gather/step/scatter machinery (leading plane
    axis carried whole), decaying regions stay awake until quiescent."""

    @staticmethod
    def _blob(topology):
        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.packed_generations import (
            pack_generations_for,
        )

        rule = parse_any("brain")
        rng = np.random.default_rng(3)
        grid = np.zeros((256, 256), np.uint8)
        grid[100:110, 100:110] = rng.integers(0, 3, size=(10, 10))
        return rule, pack_generations_for(jnp.asarray(grid), rule)

    @pytest.mark.parametrize("topology", list(Topology), ids=lambda t: t.value)
    def test_bit_identity_vs_plane_stepper(self, topology):
        from gameoflifewithactors_tpu.ops.packed_generations import (
            multi_step_packed_generations,
        )

        rule, planes = self._blob(topology)
        want = multi_step_packed_generations(jnp.array(planes), 40, rule=rule,
                                             topology=topology)
        s = SparseEngineState(jnp.array(planes), rule, topology=topology)
        s.step(40)
        np.testing.assert_array_equal(np.asarray(s.packed), np.asarray(want))
        # 4 awake tiles out of 64: the decayed field went back to sleep
        assert s.active_tiles() < 8

    def test_overflow_dense_fallback_and_adaptive(self):
        from gameoflifewithactors_tpu.ops.packed_generations import (
            multi_step_packed_generations,
        )

        rule, planes = self._blob(Topology.DEAD)
        want = multi_step_packed_generations(jnp.array(planes), 25, rule=rule,
                                             topology=Topology.DEAD)
        fixed = SparseEngineState(jnp.array(planes), rule, capacity=2,
                                  tile_rows=16, tile_words=2)
        fixed.step(25)
        np.testing.assert_array_equal(np.asarray(fixed.packed), np.asarray(want))
        adaptive = SparseEngineState(jnp.array(planes), rule,
                                     tile_rows=16, tile_words=2)
        adaptive.step(25)
        np.testing.assert_array_equal(np.asarray(adaptive.packed),
                                      np.asarray(want))

    def test_engine_facade_and_rejections(self):
        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        grid = np.zeros((64, 64), np.uint8)
        grid[30:34, 30:34] = 2
        ref = Engine(grid, "brain")
        sp = Engine(grid, "brain", backend="sparse")
        ref.step(12)
        sp.step(12)
        np.testing.assert_array_equal(ref.snapshot(), sp.snapshot())
        assert sp.population() == ref.population()
        with pytest.raises(ValueError, match="divisible by 32"):
            Engine(np.zeros((16, 48), np.uint8), "brain", backend="sparse")
        # bosco + pallas is a real kernel now; a grid too small for its
        # r*g halo falls back to the bit-sliced path with a warning
        import warnings as w

        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            Engine(np.zeros((16, 32), np.uint8), "bosco", backend="pallas")
        assert any("falling back" in str(c.message) for c in caught)

    def test_sharded_gen_sparse_bit_identity(self):
        """Per-device activity skipping on the plane stack: sharded sparse
        == sharded plane stepper == single-device, over a settling blob."""
        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        m = mesh_lib.make_mesh((2, 4))
        grid = np.zeros((32, 256), np.uint8)
        grid[10:14, 60:66] = 2
        grid[11, 61] = 1
        ref = Engine(grid, "brain")
        got = Engine(grid, "brain", mesh=m, backend="sparse")
        assert got.halo_bytes_per_gen() > 0   # flags ride the halo trip
        ref.step(24)
        got.step(24)
        np.testing.assert_array_equal(ref.snapshot(), got.snapshot())
        assert got.population() == ref.population()


# -- LtL sparse: radius-r activity tiling -------------------------------------

class TestLtLSparse:
    """Radius-r rules through the activity-tiled engine: the wake rule
    dilates by ceil(r/tile) tile rings and windows carry the rule's
    (r rows, 1 word) halo — the bit-sliced packed step per window."""

    @pytest.mark.parametrize("topology", [Topology.DEAD, Topology.TORUS])
    def test_bosco_blob_bit_identity(self, topology):
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_packed
        from gameoflifewithactors_tpu.ops.sparse import SparseEngineState

        rule = parse_any("bosco")                  # r=5 Moore
        rng = np.random.default_rng(7)
        grid = np.zeros((128, 256), np.uint8)
        grid[40:70, 60:100] = rng.integers(0, 2, size=(30, 40))
        p = jnp.asarray(bitpack.pack_np(grid))
        want = multi_step_ltl_packed(p, 24, rule=rule, topology=topology)
        st = SparseEngineState(p, rule, topology=topology)
        st.step(24)
        np.testing.assert_array_equal(np.asarray(st.packed), np.asarray(want))
        assert 0 < st.active_tiles() < st.active.size

    def test_wake_radius_crosses_small_tiles(self):
        # r=5 with 4-row tiles: influence crosses MORE than one tile
        # boundary per generation — the dy=ceil(5/4)=2 dilation case
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_packed
        from gameoflifewithactors_tpu.ops.sparse import SparseEngineState

        rule = parse_any("bosco")
        rng = np.random.default_rng(9)
        grid = np.zeros((64, 128), np.uint8)
        grid[28:36, 40:80] = rng.integers(0, 2, size=(8, 40))
        p = jnp.asarray(bitpack.pack_np(grid))
        want = multi_step_ltl_packed(p, 12, rule=rule, topology=Topology.DEAD)
        st = SparseEngineState(p, rule, tile_rows=4, tile_words=1,
                               topology=Topology.DEAD)
        st.step(12)
        np.testing.assert_array_equal(np.asarray(st.packed), np.asarray(want))

    def test_torus_seam_crossing_blob(self):
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_packed
        from gameoflifewithactors_tpu.ops.sparse import SparseEngineState

        rule = parse_any("bosco")
        rng = np.random.default_rng(13)
        grid = np.zeros((96, 192), np.uint8)
        grid[:20, 170:] = rng.integers(0, 2, size=(20, 22))  # corner seam
        p = jnp.asarray(bitpack.pack_np(grid))
        want = multi_step_ltl_packed(p, 16, rule=rule, topology=Topology.TORUS)
        st = SparseEngineState(p, rule, topology=Topology.TORUS)
        st.step(16)
        np.testing.assert_array_equal(np.asarray(st.packed), np.asarray(want))

    def test_rejections_and_engine_facade(self):
        import jax.numpy as jnp

        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.models.ltl import LtLRule
        from gameoflifewithactors_tpu.ops.sparse import SparseEngineState

        b0_ltl = LtLRule(radius=2, born=(0, 3), survive=(4, 9))
        with pytest.raises(ValueError, match="birth-from-nothing"):
            SparseEngineState(jnp.zeros((32, 1), jnp.uint32), b0_ltl)
        with pytest.raises(ValueError, match="width divisible by 32"):
            Engine(np.zeros((32, 48), np.uint8),
                   "R2,C0,M0,S6..11,B6..9,NN", backend="sparse")
        # diamond rules ride the sparse windows now (packed diamond sums)
        rng2 = np.random.default_rng(67)
        dgrid = np.zeros((64, 96), np.uint8)
        dgrid[20:40, 30:60] = rng2.integers(0, 2, size=(20, 30))
        dsp = Engine(dgrid, "R2,C0,M0,S6..11,B6..9,NN", backend="sparse",
                     topology=Topology.DEAD)
        dref = Engine(dgrid, "R2,C0,M0,S6..11,B6..9,NN", backend="dense",
                      topology=Topology.DEAD)
        dsp.step(8)
        dref.step(8)
        np.testing.assert_array_equal(dsp.snapshot(), dref.snapshot())

        # engine facade: sparse bosco == dense bosco
        rng = np.random.default_rng(3)
        grid = np.zeros((96, 128), np.uint8)
        grid[30:60, 40:90] = rng.integers(0, 2, size=(30, 50))
        sp = Engine(grid, "bosco", backend="sparse", topology=Topology.DEAD)
        ref = Engine(grid, "bosco", backend="dense", topology=Topology.DEAD)
        sp.step(10)
        ref.step(10)
        np.testing.assert_array_equal(sp.snapshot(), ref.snapshot())


class TestShardedLtLSparse:
    """Sharded per-tile sparse for radius-r rules (VERDICT r3 Weak #4):
    the tiled-sparse runner's halos, windows, and wake dilation scale
    with the rule radius; multi-state decay rides the plane-stack form."""

    @pytest.mark.parametrize("topology", [Topology.DEAD, Topology.TORUS])
    def test_binary_blob_bit_identity_and_sparsity(self, topology):
        import jax

        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        rng = np.random.default_rng(17)
        grid = np.zeros((128, 256), np.uint8)
        grid[40:70, 60:100] = rng.integers(0, 2, size=(30, 40))
        m = mesh_lib.make_mesh((2, 4), jax.devices())
        ref = Engine(grid, "bosco", topology=topology, backend="packed")
        got = Engine(grid, "bosco", topology=topology, mesh=m,
                     backend="sparse")
        ref.step(16)
        got.step(16)
        np.testing.assert_array_equal(ref.snapshot(), got.snapshot())
        # the blob must not have woken the whole universe
        n_active = int(np.asarray(got._flags).sum())
        assert 0 < n_active < got._flags.size

    @pytest.mark.parametrize("topology", [Topology.DEAD, Topology.TORUS])
    def test_multistate_planes_bit_identity(self, topology):
        import jax

        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        rng = np.random.default_rng(19)
        grid = np.zeros((64, 128), np.uint8)
        grid[20:40, 30:90] = rng.integers(0, 4, size=(20, 60))
        spec = "R2,C4,M1,S3..8,B5..9"
        m = mesh_lib.make_mesh((2, 2), jax.devices()[:4])
        ref = Engine(grid, spec, topology=topology, backend="dense")
        got = Engine(grid, spec, topology=topology, mesh=m, backend="sparse")
        assert got._ltl_planes
        ref.step(10)
        got.step(10)
        np.testing.assert_array_equal(ref.snapshot(), got.snapshot())

    def test_single_device_multistate_planes_sparse(self):
        from gameoflifewithactors_tpu import Engine

        rng = np.random.default_rng(23)
        grid = np.zeros((96, 128), np.uint8)
        grid[10:30, 10:60] = rng.integers(0, 4, size=(20, 50))
        spec = "R2,C4,M1,S3..8,B5..9"
        ref = Engine(grid, spec, backend="dense", topology=Topology.DEAD)
        # explicit fine tiles: the auto-tiled map of a test-sized grid is
        # only a handful of tiles, all awake — the sparsity claim needs a
        # map with genuinely quiet corners
        got = Engine(grid, spec, backend="sparse", topology=Topology.DEAD,
                     sparse_opts=dict(tile_rows=16, tile_words=1))
        ref.step(12)
        got.step(12)
        np.testing.assert_array_equal(ref.snapshot(), got.snapshot())
        assert 0 < got._sparse.active_tiles() < got._sparse.active.size

    def test_plane_stack_required_for_multistate(self):
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.sparse import SparseEngineState

        rule = parse_any("R2,C4,M1,S3..8,B5..9")
        with pytest.raises(ValueError, match="bit-plane stack"):
            SparseEngineState(jnp.zeros((32, 4), jnp.uint32), rule)


class TestTemporalChunkedSparse:
    """Opt-in temporal chunking (chunk_gens > 1): windows carry
    (r·g)-row halos and advance g generations per gather. Bit-identity
    must hold through period-g oscillators (the per-step change
    accumulation), global DEAD edges (the per-generation exterior
    re-zero), torus seams, every rule family, and n % g remainders."""

    @pytest.mark.parametrize("topology", [Topology.DEAD, Topology.TORUS])
    def test_soup_bit_identity_with_remainder(self, topology):
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models.rules import CONWAY
        from gameoflifewithactors_tpu.ops.packed import multi_step_packed

        rng = np.random.default_rng(3)
        grid = np.zeros((256, 256), np.uint8)
        grid[:40, :] = rng.integers(0, 2, size=(40, 256))  # touches the edge
        p = jnp.asarray(bitpack.pack(jnp.asarray(grid)))
        st = SparseEngineState(p, CONWAY, topology=topology, chunk_gens=8)
        st.step(27)                                 # 3 chunks + 3 remainder
        want = multi_step_packed(p, 27, rule=CONWAY, topology=topology)
        np.testing.assert_array_equal(np.asarray(st.packed), np.asarray(want))

    def test_period_divides_chunk_oscillator_wakes_neighbors(self):
        """A blinker has period 2 | chunk 8: endpoint comparison would
        mark its tile unchanged and stop waking neighbors — the soundness
        case for per-step change accumulation. Seed soup NEXT to a
        blinker so the neighbors matter."""
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models.rules import CONWAY
        from gameoflifewithactors_tpu.ops.packed import multi_step_packed

        grid = np.zeros((128, 128), np.uint8)
        grid[64:67, 64] = 1                         # vertical blinker
        rng = np.random.default_rng(5)
        grid[70:90, 60:80] = rng.integers(0, 2, size=(20, 20))
        p = jnp.asarray(bitpack.pack(jnp.asarray(grid)))
        st = SparseEngineState(p, CONWAY, topology=Topology.DEAD,
                               chunk_gens=8, tile_rows=8, tile_words=1)
        st.step(48)
        want = multi_step_packed(p, 48, rule=CONWAY, topology=Topology.DEAD)
        np.testing.assert_array_equal(np.asarray(st.packed), np.asarray(want))
        # an isolated pure blinker's tiles still stay awake (they change
        # every generation), but the far side of the map sleeps
        assert 0 < st.active_tiles() < st.active.size

    @pytest.mark.parametrize("spec,g", [
        ("bosco", 6),                               # r=5: g*r = 30 <= 32
        ("R2,C0,M0,S6..11,B6..9,NN", 8),            # diamond, g*r = 16
        ("brain", 8),                               # Generations planes
        ("R2,C4,M1,S3..8,B5..9", 8),                # C>=3 LtL planes
    ])
    def test_families_chunked_bit_identity(self, spec, g):
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models.generations import parse_any

        rule = parse_any(spec)
        rng = np.random.default_rng(7)
        grid = np.zeros((128, 128), np.uint8)
        grid[40:80, 30:90] = rng.integers(
            0, getattr(rule, "states", 2), size=(40, 60))
        if getattr(rule, "states", 2) > 2:
            from gameoflifewithactors_tpu.ops.generations import (
                multi_step_generations,
            )
            from gameoflifewithactors_tpu.ops.ltl import multi_step_ltl
            from gameoflifewithactors_tpu.ops.packed_generations import (
                pack_generations_for,
                unpack_generations,
            )
            from gameoflifewithactors_tpu.models.ltl import LtLRule

            dense_run = (multi_step_ltl if isinstance(rule, LtLRule)
                         else multi_step_generations)
            want = np.asarray(dense_run(
                jnp.asarray(grid), 2 * g + 3, rule=rule,
                topology=Topology.DEAD))
            st = SparseEngineState(
                pack_generations_for(jnp.asarray(grid), rule), rule,
                topology=Topology.DEAD, chunk_gens=g)
            st.step(2 * g + 3)
            got = np.asarray(unpack_generations(st.packed))
        else:
            from gameoflifewithactors_tpu.ops.packed_ltl import (
                multi_step_ltl_packed,
            )

            p = jnp.asarray(bitpack.pack(jnp.asarray(grid)))
            want_p = multi_step_ltl_packed(p, 2 * g + 3, rule=rule,
                                           topology=Topology.DEAD)
            want = np.asarray(bitpack.unpack(want_p))
            st = SparseEngineState(p, rule, topology=Topology.DEAD,
                                   chunk_gens=g)
            st.step(2 * g + 3)
            got = np.asarray(bitpack.unpack(st.packed))
        np.testing.assert_array_equal(got, want, err_msg=spec)

    def test_chunk_validation(self):
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.sparse import max_chunk_gens

        bosco = parse_any("bosco")
        assert max_chunk_gens(bosco) == 6           # 32 // 5
        p = jnp.zeros((64, 4), jnp.uint32)
        with pytest.raises(ValueError, match="g\\*radius <= 32"):
            SparseEngineState(p, bosco, chunk_gens=7)
        with pytest.raises(ValueError, match="ring"):
            SparseEngineState(jnp.zeros((16, 4), jnp.uint32),
                              parse_any("bosco"), chunk_gens=6)  # 30 > 16

    @pytest.mark.parametrize("topology", [Topology.DEAD, Topology.TORUS])
    def test_chunked_overflow_and_escalation_paths(self, topology):
        """Capacity overflow with chunk_gens > 1: the bulk/remainder/
        dense-fallback interplay and _build_dense_once's sub-ring slicing
        (ring > r) must stay exact. Fixed capacity 2 forces the dense
        fallback; an adaptive engine under the same soup escalates —
        both must match the dense reference bit-for-bit."""
        import jax.numpy as jnp

        from gameoflifewithactors_tpu.models.rules import CONWAY
        from gameoflifewithactors_tpu.ops.packed import multi_step_packed

        rng = np.random.default_rng(13)
        grid = rng.integers(0, 2, size=(64, 128), dtype=np.uint8)  # hot soup
        p = jnp.asarray(bitpack.pack(jnp.asarray(grid)))
        want = multi_step_packed(p, 11, rule=CONWAY, topology=topology)

        fixed = SparseEngineState(p, CONWAY, topology=topology,
                                  chunk_gens=4, tile_rows=16, tile_words=1,
                                  capacity=2)
        fixed.step(11)                              # dense fallback, ring > r
        np.testing.assert_array_equal(np.asarray(fixed.packed),
                                      np.asarray(want))

        adaptive = SparseEngineState(p, CONWAY, topology=topology,
                                     chunk_gens=4, tile_rows=16, tile_words=1)
        adaptive._set_capacity(2)                   # badly undersized start
        adaptive.step(11)
        np.testing.assert_array_equal(np.asarray(adaptive.packed),
                                      np.asarray(want))
        assert adaptive.capacity > 2                # escalated, not stuck
