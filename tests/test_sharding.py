"""Sharding-equivalence tests: the multi-node story without a cluster.

Runs the sharded engine on 8 fake CPU devices (conftest.py) and asserts
bit-identity with the single-device engine (SURVEY.md §5): halo-exchange
bugs show up as edge-row/corner divergence, making this suite the "race
detector" for the communication layer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gameoflifewithactors_tpu.models import seeds
from gameoflifewithactors_tpu.models.rules import CONWAY, DAY_AND_NIGHT, HIGHLIFE
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.packed import multi_step_packed
from gameoflifewithactors_tpu.ops.stencil import Topology, multi_step
from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
from gameoflifewithactors_tpu.parallel import sharded


def _mesh(shape):
    n = shape[0] * shape[1]
    return mesh_lib.make_mesh(shape, jax.devices()[:n])


def test_eight_fake_devices_present():
    assert len(jax.devices()) == 8, "conftest must provide 8 fake CPU devices"


def test_factor2d():
    assert mesh_lib.factor2d(8) == (2, 4)
    assert mesh_lib.factor2d(4) == (2, 2)
    assert mesh_lib.factor2d(7) == (1, 7)
    assert mesh_lib.factor2d(64) == (8, 8)


def test_mesh_shape_validation():
    with pytest.raises(ValueError):
        mesh_lib.make_mesh((3, 3), jax.devices()[:8])
    with pytest.raises(ValueError):
        mesh_lib.check_divisible((30, 64), _mesh((4, 2)))


@pytest.mark.parametrize("mesh_shape", [(2, 4), (4, 2), (8, 1), (1, 8), (2, 2)])
@pytest.mark.parametrize("topology", list(Topology), ids=lambda t: t.value)
def test_packed_sharded_bit_identity(mesh_shape, topology):
    """Random soup, 8 generations: sharded == single-device, bit for bit."""
    rng = np.random.default_rng(123)
    g = rng.integers(0, 2, size=(32, 256), dtype=np.uint8)
    p_single = bitpack.pack(jnp.asarray(g))
    want = np.asarray(bitpack.unpack(multi_step_packed(p_single, 8, rule=CONWAY, topology=topology)))

    m = _mesh(mesh_shape)
    p = mesh_lib.device_put_sharded_grid(bitpack.pack(jnp.asarray(g)), m)
    run = sharded.make_multi_step_packed(m, CONWAY, topology)
    got = np.asarray(bitpack.unpack(run(p, 8)))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("rule", [HIGHLIFE, DAY_AND_NIGHT], ids=str)
@pytest.mark.parametrize("topology", list(Topology), ids=lambda t: t.value)
def test_dense_sharded_matches_single(rule, topology):
    rng = np.random.default_rng(5)
    g = rng.integers(0, 2, size=(64, 64), dtype=np.uint8)
    want = np.asarray(multi_step(jnp.asarray(g), 6, rule=rule, topology=topology))

    m = _mesh((2, 4))
    x = mesh_lib.device_put_sharded_grid(jnp.asarray(g), m)
    run = sharded.make_multi_step_dense(m, rule, topology)
    np.testing.assert_array_equal(np.asarray(run(x, 6)), want)


def test_glider_crosses_tile_corner():
    """A glider flying SE through the interior 4-corner point of a (2, 2)
    mesh exercises the diagonal (corner) halo path — the classic bug."""
    m = _mesh((2, 2))
    g = seeds.seeded((64, 64), "glider", 28, 28)  # just NW of the center
    want_dense = np.asarray(multi_step(jnp.asarray(g), 24, rule=CONWAY))

    p = mesh_lib.device_put_sharded_grid(bitpack.pack(jnp.asarray(g)), m)
    run = sharded.make_multi_step_packed(m, CONWAY, Topology.TORUS)
    got = np.asarray(bitpack.unpack(run(p, 24)))
    np.testing.assert_array_equal(got, want_dense)
    assert got.sum() == 5  # still a glider


def test_glider_wraps_global_torus_across_shards():
    """Torus wrap must cross the *global* boundary, not each tile's."""
    m = _mesh((2, 4))
    g = seeds.seeded((32, 128), "glider", 28, 124)  # at the SE global corner
    want = np.asarray(multi_step(jnp.asarray(g), 64, rule=CONWAY))
    p = mesh_lib.device_put_sharded_grid(bitpack.pack(jnp.asarray(g)), m)
    run = sharded.make_multi_step_packed(m, CONWAY, Topology.TORUS)
    np.testing.assert_array_equal(np.asarray(bitpack.unpack(run(p, 64))), want)


def test_single_step_builder():
    m = _mesh((2, 4))
    g = seeds.seeded((16, 256), "blinker", 8, 100)
    p = mesh_lib.device_put_sharded_grid(bitpack.pack(jnp.asarray(g)), m)
    step = sharded.make_step_packed(m, CONWAY, Topology.TORUS)
    two = step(step(p))
    np.testing.assert_array_equal(np.asarray(bitpack.unpack(two)), g)


def test_output_stays_sharded():
    """The stepped grid must keep its 2D sharding (no implicit gather)."""
    m = _mesh((2, 4))
    p = mesh_lib.device_put_sharded_grid(jnp.zeros((32, 8), jnp.uint32), m)
    out = sharded.make_step_packed(m, CONWAY, Topology.TORUS)(p)
    assert out.sharding == mesh_lib.grid_sharding(m)


# -- multi-slice (DCN) layout -------------------------------------------------

def test_multislice_layout_row_bands():
    """With 2 pretend slices of 4 devices, slices must own contiguous row
    bands so only N/S halos cross the slice (DCN) boundary."""
    devs = jax.devices()
    ids = [0, 0, 0, 0, 1, 1, 1, 1]
    arr = mesh_lib.order_devices_for_slices(devs, (4, 2), ids)
    by_dev = dict(zip(devs, ids))
    for r in range(4):
        row_slices = {by_dev[d] for d in arr[r]}
        assert len(row_slices) == 1, f"mesh row {r} spans slices {row_slices}"
    # band order: slice 0 rows first, then slice 1
    assert by_dev[arr[0, 0]] == 0 and by_dev[arr[3, 0]] == 1


def test_multislice_layout_interleaved_ids():
    devs = jax.devices()
    arr = mesh_lib.order_devices_for_slices(devs, (2, 4), [0, 1, 0, 1, 0, 1, 0, 1])
    ids = dict(zip(devs, [0, 1, 0, 1, 0, 1, 0, 1]))
    assert {ids[d] for d in arr[0]} == {0}
    assert {ids[d] for d in arr[1]} == {1}


def test_multislice_layout_rejects_bad_shapes():
    devs = jax.devices()
    two_slices = [0, 0, 0, 0, 1, 1, 1, 1]
    with pytest.raises(ValueError):  # slice boundary would cut a mesh row
        mesh_lib.order_devices_for_slices(devs, (1, 8), two_slices)
    with pytest.raises(ValueError):  # uneven devices per slice
        mesh_lib.order_devices_for_slices(devs, (4, 2), [0, 0, 0, 1, 1, 1, 1, 1])
    with pytest.raises(ValueError):  # id count mismatch
        mesh_lib.order_devices_for_slices(devs, (4, 2), [0, 1])


def test_factor2d_sliced_prefers_slice_compatible_shapes():
    # 32 devices on 8 slices: plain factor2d gives (4, 8), which cannot band
    # (4 per slice < 8 per row); the sliced factorization must pick ny | 4
    assert mesh_lib.factor2d_sliced(32, 8) == (8, 4)
    assert mesh_lib.factor2d_sliced(8, 2) == (2, 4)  # 1 row per slice band
    assert mesh_lib.factor2d_sliced(8, 1) == (2, 4)  # degenerates to factor2d


def test_make_mesh_default_shape_is_slice_compatible():
    m = mesh_lib.make_mesh(devices=jax.devices(), slice_ids=[0, 0, 0, 0, 1, 1, 1, 1])
    assert (m.shape[mesh_lib.ROW_AXIS], m.shape[mesh_lib.COL_AXIS]) == (2, 4)


def test_make_mesh_falls_back_when_banding_impossible():
    # explicit shape (1, 8) cannot band 2 slices into row bands; with
    # auto-detected ids it must warn and fall back, not crash
    import warnings as w

    devs = jax.devices()
    orig = mesh_lib.slice_ids_of
    mesh_lib.slice_ids_of = lambda ds: [0, 0, 0, 0, 1, 1, 1, 1]
    try:
        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            m = mesh_lib.make_mesh((1, 8), devs)
        assert any("falling back" in str(c.message) for c in caught)
        assert m.shape[mesh_lib.COL_AXIS] == 8
        with pytest.raises(ValueError):  # explicit slice_ids: no fallback
            mesh_lib.make_mesh((1, 8), devs, slice_ids=[0, 0, 0, 0, 1, 1, 1, 1])
    finally:
        mesh_lib.slice_ids_of = orig


def test_multislice_mesh_bit_identity():
    """A slice-banded mesh is just a device reordering: results must be
    bit-identical to the single-device engine."""
    m = mesh_lib.make_mesh((4, 2), jax.devices(), slice_ids=[0, 0, 0, 0, 1, 1, 1, 1])
    rng = np.random.default_rng(5)
    g = rng.integers(0, 2, size=(64, 64), dtype=np.uint8)
    want = np.asarray(bitpack.unpack(
        multi_step_packed(bitpack.pack(jnp.asarray(g)), 16, rule=CONWAY, topology=Topology.TORUS)))
    p = mesh_lib.device_put_sharded_grid(bitpack.pack(jnp.asarray(g)), m)
    run = sharded.make_multi_step_packed(m, CONWAY, Topology.TORUS)
    np.testing.assert_array_equal(np.asarray(bitpack.unpack(run(p, 16))), want)


class TestCommunicationAvoiding:
    """make_multi_step_packed_deep: one exchange per g generations."""

    def _mesh(self, shape=(2, 4)):
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        return mesh_lib.make_mesh(shape)

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    @pytest.mark.parametrize("g", [1, 3, 8, 32])
    def test_bit_identity_vs_per_gen_exchange(self, topology, g):
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        m = self._mesh()
        rng = np.random.default_rng(17)
        grid = rng.integers(0, 2, size=(64, 256), dtype=np.uint8)
        p_single = bitpack.pack(jnp.asarray(grid))
        chunks = 3
        want = np.asarray(bitpack.unpack(multi_step_packed(
            p_single, chunks * g, rule=CONWAY, topology=topology)))

        p = mesh_lib.device_put_sharded_grid(p_single, m)
        run = sharded.make_multi_step_packed_deep(
            m, CONWAY, topology, gens_per_exchange=g)
        got = np.asarray(bitpack.unpack(run(p, chunks)))
        np.testing.assert_array_equal(got, want)

    def test_glider_crosses_tile_corner_under_deep_halo(self):
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        m = self._mesh()
        # a glider aimed through the (row, col) tile corner at (32, 64)
        grid = np.asarray(seeds.seeded((64, 256), "glider", 28, 60))
        p_single = bitpack.pack(jnp.asarray(grid))
        want = np.asarray(bitpack.unpack(multi_step_packed(
            p_single, 24, rule=CONWAY, topology=Topology.TORUS)))
        run = sharded.make_multi_step_packed_deep(
            m, CONWAY, Topology.TORUS, gens_per_exchange=8)
        got = np.asarray(bitpack.unpack(
            run(mesh_lib.device_put_sharded_grid(p_single, m), 3)))
        np.testing.assert_array_equal(got, want)

    def test_rejects_out_of_range_depth(self):
        m = self._mesh()
        with pytest.raises(ValueError, match=r"\[1, 32\]"):
            sharded.make_multi_step_packed_deep(m, CONWAY, gens_per_exchange=33)
        with pytest.raises(ValueError, match=r"\[1, 32\]"):
            sharded.make_multi_step_packed_deep(m, CONWAY, gens_per_exchange=0)

    def test_engine_facade_gens_per_exchange(self):
        from gameoflifewithactors_tpu import Engine

        m = self._mesh()
        grid = np.asarray(seeds.seeded((64, 256), "glider", 10, 10))
        want = Engine(grid, "conway", mesh=m)
        got = Engine(grid, "conway", mesh=m, gens_per_exchange=8)
        # 19 = 2 deep chunks + 3 per-gen remainder
        want.step(19)
        got.step(19)
        np.testing.assert_array_equal(want.snapshot(), got.snapshot())
        with pytest.raises(ValueError, match="sharded packed and pallas"):
            Engine(grid, "conway", gens_per_exchange=8)  # no mesh
        with pytest.raises(ValueError, match="sharded packed and pallas"):
            Engine(grid, "brain", mesh=m, gens_per_exchange=8)  # multi-state

    def test_deep_mode_halo_estimate_and_validation(self):
        from gameoflifewithactors_tpu import Engine

        m = self._mesh()
        grid = np.zeros((64, 256), np.uint8)
        base = Engine(grid, "conway", mesh=m).halo_bytes_per_gen()
        deep = Engine(grid, "conway", mesh=m,
                      gens_per_exchange=8).halo_bytes_per_gen()
        # one depth-8 exchange per 8 gens amortizes well below per-gen strips
        assert 0 < deep < base
        with pytest.raises(ValueError, match=">= 1"):
            Engine(grid, "conway", mesh=m, gens_per_exchange=0)


class TestShardedPallas:
    """make_multi_step_pallas: row-band sharding over the Mosaic slab kernel.

    Interpret mode on the 8-fake-CPU rig (the kernel itself is proven
    native-vs-XLA bit-identical on chip in results/tpu_worklist.json
    pallas_identity); these tests pin the *composition* — halo depth, slab
    zero-fill, crop — against the single-device packed path.
    """

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    @pytest.mark.parametrize("mesh_shape,grid_h,g", [
        ((8, 1), 64, 1),
        ((8, 1), 64, 3),
        ((8, 1), 64, 8),
        ((4, 1), 192, 40),  # g > 32: no halo-word creep cap on row bands
        ((2, 4), 64, 8),    # 2D meshes flatten into nx*ny bands
        ((4, 2), 64, 3),    # (VERDICT r3 Missing #4)
    ])
    def test_bit_identity_vs_single_device(self, mesh_shape, grid_h, g,
                                           topology):
        m = _mesh(mesh_shape)
        rng = np.random.default_rng(29)
        grid = rng.integers(0, 2, size=(grid_h, 256), dtype=np.uint8)
        p_single = bitpack.pack(jnp.asarray(grid))
        chunks = 3
        want = np.asarray(bitpack.unpack(multi_step_packed(
            p_single, chunks * g, rule=CONWAY, topology=topology)))

        p = mesh_lib.device_put_sharded_grid(p_single, m,
                                             banded=mesh_shape[1] > 1)
        run = sharded.make_multi_step_pallas(
            m, CONWAY, topology=topology, gens_per_exchange=g, interpret=True)
        got = np.asarray(bitpack.unpack(run(p, chunks)))
        np.testing.assert_array_equal(got, want)

    def test_dead_edge_activity_on_boundary_bands(self):
        """DEAD on the band runner: dense soup touching the global top and
        bottom edges — births just outside the edge must NOT feed back
        (VERDICT round-2 item #4). The top/bottom rows live on the edge
        devices, whose SMEM edge code realizes the permanently-dead
        exterior inside the kernel's per-generation loop."""
        m = _mesh((8, 1))
        rng = np.random.default_rng(31)
        grid = np.ones((64, 256), dtype=np.uint8)  # max edge interaction
        grid[1::2, ::3] = 0
        p_single = bitpack.pack(jnp.asarray(grid))
        want = np.asarray(bitpack.unpack(multi_step_packed(
            p_single, 24, rule=CONWAY, topology=Topology.DEAD)))
        run = sharded.make_multi_step_pallas(
            m, CONWAY, topology=Topology.DEAD, gens_per_exchange=8,
            interpret=True)
        got = np.asarray(bitpack.unpack(
            run(mesh_lib.device_put_sharded_grid(p_single, m), 3)))
        np.testing.assert_array_equal(got, want)

    def test_glider_wraps_vertical_band_boundaries(self):
        """A glider flying SE through band boundaries AND the global torus
        wrap: exercises the exchanged halo rows and the slab crop."""
        m = _mesh((8, 1))
        grid = np.asarray(seeds.seeded((64, 256), "glider", 58, 60))
        p_single = bitpack.pack(jnp.asarray(grid))
        want = np.asarray(bitpack.unpack(multi_step_packed(
            p_single, 48, rule=CONWAY, topology=Topology.TORUS)))
        run = sharded.make_multi_step_pallas(
            m, CONWAY, gens_per_exchange=8, interpret=True)
        got = np.asarray(bitpack.unpack(
            run(mesh_lib.device_put_sharded_grid(p_single, m), 6)))
        np.testing.assert_array_equal(got, want)

    def test_rejects_exchange_deeper_than_band(self):
        m = _mesh((8, 1))
        run = sharded.make_multi_step_pallas(
            m, CONWAY, gens_per_exchange=16, interpret=True)
        p = mesh_lib.device_put_sharded_grid(
            bitpack.pack(jnp.zeros((64, 256), jnp.uint8)), m)  # band h = 8
        with pytest.raises(ValueError, match="band height"):
            run(p, 1)
        # same trace-time guard on the flattened 2D decomposition (bands
        # of 64/8 = 8 rows over a (2, 4) mesh)
        m2 = _mesh((2, 4))
        run2 = sharded.make_multi_step_pallas(
            m2, CONWAY, gens_per_exchange=16, interpret=True)
        p2 = mesh_lib.device_put_sharded_grid(
            bitpack.pack(jnp.zeros((64, 256), jnp.uint8)), m2, banded=True)
        with pytest.raises(ValueError, match="band height"):
            run2(p2, 1)

    @pytest.mark.parametrize("mesh_shape", [(8, 1), (2, 4)])
    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    def test_engine_facade_pallas_mesh(self, mesh_shape, topology):
        from gameoflifewithactors_tpu import Engine

        m = _mesh(mesh_shape)
        grid = np.asarray(seeds.seeded((64, 256), "glider", 10, 10))
        want = Engine(grid, "conway", mesh=m, topology=topology)  # SWAR
        got = Engine(grid, "conway", mesh=m, backend="pallas",
                     topology=topology, gens_per_exchange=8)
        want.step(19)
        got.step(19)                                   # 2 chunks + 3 remainder
        np.testing.assert_array_equal(want.snapshot(), got.snapshot())
        hb = got.halo_bytes_per_gen()
        if mesh_shape[1] == 1:
            # ny=1: depth-g exchange moves the same bytes as g 1-deep
            # trips (the win is 1/g the collective count); must not grow
            assert 0 < hb <= want.halo_bytes_per_gen()
        else:
            # 2D flattened bands: the exact figure is pinned against the
            # compiled HLO in test_halo_bytes.py
            # test_band_estimate_matches_compiled_hlo
            assert hb > 0

    def test_engine_band_path_takes_width_not_sharding_over_ny(self):
        """A width that packs into words but does NOT divide over the
        column axis is fine on the band path (bands span the full width)
        — the very case the 2D-tile runners must reject."""
        from gameoflifewithactors_tpu import Engine

        rng = np.random.default_rng(43)
        grid = rng.integers(0, 2, size=(128, 224), dtype=np.uint8)  # 7 words
        m = _mesh((2, 4))
        with pytest.raises(ValueError, match="not divisible over mesh"):
            Engine(grid, "conway", mesh=m, backend="packed")
        ref = Engine(grid, "conway")
        got = Engine(grid, "conway", mesh=m, backend="pallas")
        ref.step(9)
        got.step(9)                                    # 1 chunk + 1 remainder
        np.testing.assert_array_equal(ref.snapshot(), got.snapshot())

    def test_rejects_exchange_deeper_than_blocks(self):
        """g > block_rows breaks the 3-segment DMA contiguity contract and
        must be rejected, not silently mis-assembled (review finding)."""
        from gameoflifewithactors_tpu.ops.pallas_stencil import (
            band_supported,
            make_pallas_slab_step,
        )

        with pytest.raises(ValueError, match="<= block_rows"):
            make_pallas_slab_step(CONWAY, Topology.TORUS, (96, 8), gens=24,
                                  block_rows=16, interpret=True)
        # the auto gate agrees with the kernel's own validation
        assert not band_supported(16, 24, native=True)
        assert band_supported(2048, 8, native=True)
        assert not band_supported(2048, 12, native=True)   # g % 8
        assert not band_supported(2044, 8, native=True)    # band % 8
        assert band_supported(48, 24, native=False)        # interpret: ok


class TestShardedGenerationsPallas:
    """Row-band Generations kernel runner (interpret mode on the CPU rig)."""

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    @pytest.mark.parametrize("mesh_shape,grid_h,g", [
        ((8, 1), 64, 3),
        ((4, 1), 64, 8),
        ((2, 4), 64, 3),    # flattened 2D band decomposition
    ])
    def test_bit_identity_vs_single_device(self, mesh_shape, grid_h, g,
                                           topology):
        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.packed_generations import (
            multi_step_packed_generations,
            pack_generations_for,
        )

        rule = parse_any("brain")
        m = _mesh(mesh_shape)
        rng = np.random.default_rng(37)
        grid = rng.integers(0, rule.states, size=(grid_h, 96), dtype=np.uint8)
        planes = pack_generations_for(jnp.asarray(grid), rule)
        chunks = 3
        want = np.asarray(multi_step_packed_generations(
            planes, chunks * g, rule=rule, topology=topology))

        p = mesh_lib.device_put_sharded_grid(planes, m,
                                             banded=mesh_shape[1] > 1)
        run = sharded.make_multi_step_generations_pallas(
            m, rule, topology=topology, gens_per_exchange=g, interpret=True)
        got = np.asarray(run(p, chunks))
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("mesh_shape", [(8, 1), (2, 4)])
    def test_engine_facade_generations_band(self, mesh_shape):
        from gameoflifewithactors_tpu import Engine

        m = _mesh(mesh_shape)
        rng = np.random.default_rng(41)
        grid = rng.integers(0, 3, size=(64, 96), dtype=np.uint8)
        # the reference runner shards 2D tiles, which the 96-cell width
        # cannot feed on a (2, 4) mesh — compare against single-device
        ref = Engine(grid, "brain")
        got = Engine(grid, "brain", mesh=m, backend="pallas",
                     gens_per_exchange=8)
        ref.step(19)
        got.step(19)                                      # 2 chunks + 3 rem
        np.testing.assert_array_equal(ref.snapshot(), got.snapshot())


class TestBandedPerGen:
    """make_multi_step_banded: the per-generation XLA companion of the
    band-kernel runners (remainder steps on any mesh shape)."""

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    @pytest.mark.parametrize("mesh_shape", [(8, 1), (2, 4), (4, 2)])
    def test_binary_bit_identity(self, mesh_shape, topology):
        m = _mesh(mesh_shape)
        rng = np.random.default_rng(61)
        grid = rng.integers(0, 2, size=(64, 256), dtype=np.uint8)
        p = bitpack.pack(jnp.asarray(grid))
        want = multi_step_packed(p, 7, rule=CONWAY, topology=topology)
        run = sharded.make_multi_step_banded(m, CONWAY, topology)
        got = run(mesh_lib.device_put_sharded_grid(
            p, m, banded=mesh_shape[1] > 1), 7)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    def test_generations_and_ltl_families(self, topology):
        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.packed_generations import (
            multi_step_packed_generations,
            pack_generations_for,
        )
        from gameoflifewithactors_tpu.ops.packed_ltl import (
            multi_step_ltl_packed,
        )

        m = _mesh((2, 4))
        rng = np.random.default_rng(67)
        brain = parse_any("brain")
        grid = rng.integers(0, brain.states, size=(64, 96), dtype=np.uint8)
        planes = pack_generations_for(jnp.asarray(grid), brain)
        want = multi_step_packed_generations(planes, 5, rule=brain,
                                             topology=topology)
        run = sharded.make_multi_step_banded(m, brain, topology)
        got = run(mesh_lib.device_put_sharded_grid(planes, m, banded=True), 5)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

        bosco = parse_any("bosco")      # r=5: bands of 32 rows >= r
        p = bitpack.pack(jnp.asarray(
            rng.integers(0, 2, size=(256, 96), dtype=np.uint8)))
        want = multi_step_ltl_packed(p, 3, rule=bosco, topology=topology)
        run = sharded.make_multi_step_banded(m, bosco, topology)
        got = run(mesh_lib.device_put_sharded_grid(p, m, banded=True), 3)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_rejects_band_shorter_than_radius(self):
        from gameoflifewithactors_tpu.models.generations import parse_any

        m = _mesh((2, 4))
        bosco = parse_any("bosco")      # r=5 > 32/8 = 4-row bands
        run = sharded.make_multi_step_banded(m, bosco, Topology.TORUS)
        p = mesh_lib.device_put_sharded_grid(
            bitpack.pack(jnp.zeros((32, 96), jnp.uint8)), m, banded=True)
        with pytest.raises(ValueError, match="band height"):
            run(p, 1)
