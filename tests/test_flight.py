"""Flight recorder (obs/flight.py): the black box that dumps on trouble.

Covers the ISSUE-3 acceptance points: dump-on-stall (chained off the
watchdog, naming the last-completed span), dump-on-signal (in-process
handler chain, plus a real SIGTERM against a stepping CLI run), the
coordinator-loop exception hook, the bounded tape, and the clean-exit
path leaving no dump.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from gameoflifewithactors_tpu.obs import flight as flight_lib
from gameoflifewithactors_tpu.obs import spans as spans_lib
from gameoflifewithactors_tpu.obs.compile import CompileEvent, CompileEventLog
from gameoflifewithactors_tpu.obs.flight import FlightRecorder, load_dump
from gameoflifewithactors_tpu.obs.watchdog import StallWatchdog

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_tape_is_bounded_and_dump_round_trips(tmp_path):
    tr = spans_lib.SpanTracer()
    log = CompileEventLog()
    fr = FlightRecorder(str(tmp_path / "f.jsonl"), max_records=4,
                        tracer=tr, compile_log=log)
    fr.install(signals=False)
    try:
        for i in range(10):
            with tr.span(f"s{i}"):
                pass
            fr.on_step({"generation": i, "generations_stepped": 1,
                        "wall_seconds": 0.01,
                        "cell_updates_per_sec": 1e6})
        log.record(CompileEvent(
            runner="r", signature="uint32[8,8]", wall_seconds=0.5,
            cache_miss=True, donated=False, t0=0.0, t1=0.5))
        path = fr.dump("unit test")
    finally:
        fr.uninstall()
    d = load_dump(path)
    assert d["flight"]["reason"] == "unit test"
    assert d["flight"]["last_completed_span"] == "s9"
    # bounded tape: only the last 4 of each survive
    assert [m["generation"] for m in d["step_metrics"]] == [6, 7, 8, 9]
    assert [s["name"] for s in d["span"]] == ["s6", "s7", "s8", "s9"]
    assert [c["runner"] for c in d["compile_event"]] == ["r"]
    assert "registry" in d
    # a second dump overwrites with fresher tape, not appends
    with tr.span("s10"):
        pass
    d2 = load_dump(fr.dump("again"))
    assert d2["flight"]["last_completed_span"] == "s10"
    assert fr.dumps == 2


def test_listener_taps_survive_tracer_clear(tmp_path):
    """The tape is live-tapped: clearing the tracer (a fresh telemetry
    session does) must not erase what the recorder already taped."""
    tr = spans_lib.SpanTracer()
    fr = FlightRecorder(str(tmp_path / "f.jsonl"), tracer=tr)
    fr.install(signals=False)
    try:
        with tr.span("before.clear"):
            pass
        tr.clear()
        d = load_dump(fr.dump("post-clear"))
    finally:
        fr.uninstall()
    assert [s["name"] for s in d["span"]] == ["before.clear"]
    # uninstalled: later spans are not taped
    with tr.span("after.uninstall"):
        pass
    d2 = load_dump(fr.dump("detached"))
    assert [s["name"] for s in d2["span"]] == ["before.clear"]


def test_dump_on_watchdog_stall_names_last_span(tmp_path):
    tr = spans_lib.SpanTracer()
    wd = StallWatchdog(0.05, tracer=tr, on_stall=lambda ev: None)
    fr = FlightRecorder(str(tmp_path / "f.jsonl"), tracer=tr)
    fr.install(signals=False, watchdog=wd)
    try:
        with wd:
            with tr.span("engine.step"):
                pass
            with wd.watch("tick@gen0+1"):
                deadline = time.perf_counter() + 2.0
                while not fr.dumps and time.perf_counter() < deadline:
                    time.sleep(0.01)
    finally:
        fr.uninstall()
    assert fr.dumps == 1
    d = load_dump(fr.path)
    assert d["flight"]["reason"] == "watchdog stall: tick@gen0+1"
    assert d["flight"]["last_completed_span"] == "engine.step"
    assert d["stall"][0]["label"] == "tick@gen0+1"


def test_dump_on_signal_chains_previous_handler(tmp_path):
    got = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: got.append(s))
    fr = FlightRecorder(str(tmp_path / "f.jsonl"))
    try:
        fr.install(signals=True)
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.perf_counter() + 2.0
        while not got and time.perf_counter() < deadline:
            time.sleep(0.01)
    finally:
        fr.uninstall()
        signal.signal(signal.SIGTERM, prev)
    assert got == [signal.SIGTERM], "previous handler must still run"
    assert fr.dumps == 1 and fr.last_dump_reason == "signal SIGTERM"
    assert load_dump(fr.path)["flight"]["reason"] == "signal SIGTERM"
    # uninstall restored the pre-install handler
    assert signal.getsignal(signal.SIGTERM) is prev


def test_coordinator_exception_leaves_dump(tmp_path):
    from gameoflifewithactors_tpu.coordinator import GridCoordinator

    coord = GridCoordinator((24, 32), "B3/S23", random_fill=0.3)
    fr = flight_lib.arm(FlightRecorder(str(tmp_path / "f.jsonl")))
    try:
        coord.subscribe(lambda frame: (_ for _ in ()).throw(
            RuntimeError("subscriber died")))
        with pytest.raises(RuntimeError, match="subscriber died"):
            coord.tick(1)
    finally:
        flight_lib.disarm()
    assert flight_lib.active_flight_recorder() is None
    d = load_dump(fr.path)
    assert d["flight"]["reason"].startswith(
        "exception in coordinator loop: RuntimeError")
    # the taped spans show how far the tick got before dying
    assert any(s["name"] == "engine.step" for s in d["span"])


def test_telemetry_session_clean_exit_leaves_no_dump(tmp_path):
    from gameoflifewithactors_tpu.coordinator import GridCoordinator
    from gameoflifewithactors_tpu.obs.report import begin_run_telemetry

    flight = str(tmp_path / "f.jsonl")
    telem = begin_run_telemetry(stall_deadline=30.0, flight_path=flight)
    assert flight_lib.active_flight_recorder() is telem.flight
    coord = GridCoordinator((24, 32), "B3/S23", random_fill=0.3)
    telem.attach(coord)
    coord.run(4)
    rep = telem.finish(engine=coord.engine)
    assert flight_lib.active_flight_recorder() is None
    assert not os.path.exists(flight), "clean runs leave no crash report"
    assert rep.step_metrics  # the session still reported normally


def test_cli_sigterm_leaves_flight_dump(tmp_path):
    """The acceptance scenario end-to-end: SIGTERM a *stepping* CLI run;
    the process dies by the signal AND leaves a flight dump naming the
    last completed span and the final StepMetrics window."""
    out = tmp_path / "run.json"
    cmd = [sys.executable, "-m", "gameoflifewithactors_tpu",
           "--grid", "64x64", "--seed", "random", "--steps", "1000000",
           "--rate", "25", "--metrics", "jsonl",
           "--telemetry-out", str(out)]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    p = subprocess.Popen(cmd, cwd=_REPO, env=env, stderr=subprocess.PIPE,
                         text=True)
    try:
        # --metrics jsonl streams a record per tick to stderr: the first
        # one proves the run is stepping (past construction + compile)
        deadline = time.monotonic() + 120
        for line in p.stderr:
            if '"generation"' in line or time.monotonic() > deadline:
                break
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    assert rc == -signal.SIGTERM, "handler must re-raise, not swallow"
    flight = str(out) + ".flight.jsonl"
    assert os.path.exists(flight)
    d = load_dump(flight)
    assert d["flight"]["reason"] == "signal SIGTERM"
    assert d["flight"]["last_completed_span"]
    assert d["step_metrics"], "final StepMetrics window must be taped"
    assert d["step_metrics"][-1]["generation"] >= 1
    assert not os.path.exists(str(out)), \
        "a killed run has no RunReport — the flight dump IS the artifact"


def test_load_dump_tolerates_blank_and_unknown_lines(tmp_path):
    path = tmp_path / "d.jsonl"
    path.write_text('{"type": "flight", "reason": "x"}\n\n'
                    '{"type": "mystery", "a": 1}\n')
    d = load_dump(str(path))
    assert d["flight"]["reason"] == "x"
    assert d["span"] == []


def test_signal_chain_preserves_both_handlers(tmp_path):
    """Regression: a hook chained on TOP of an armed flight recorder
    must fire AND still reach the recorder's dump — and uninstalling in
    reverse order leaves the original disposition untouched. (The bug
    class: a second SIGTERM installer silently dropping the first.)"""
    hits = []
    prev = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, signal.SIG_IGN)  # survivable base
    fr = FlightRecorder(str(tmp_path / "f.jsonl"))
    fr.install(signals=True)
    unchain = flight_lib.chain_signal_handler(
        signal.SIGTERM, lambda signum, frame: hits.append(signum))
    try:
        signal.raise_signal(signal.SIGTERM)
        assert hits == [signal.SIGTERM], "the top hook must fire"
        assert fr.dumps == 1, "the chained recorder must still dump"
        assert fr.last_dump_reason == "signal SIGTERM"
        assert os.path.exists(str(tmp_path / "f.jsonl"))
    finally:
        unchain()
        fr.uninstall()
        signal.signal(signal.SIGTERM, prev)
    assert signal.getsignal(signal.SIGTERM) is prev


def test_recorder_chains_onto_graceful_handler(tmp_path):
    """The serve process shape (serve/frontend.py main): the graceful
    stop handler installs FIRST, the recorder arms second — one SIGTERM
    must both dump the tape and request the clean shutdown."""
    stopped = []
    prev = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, lambda s, f: stopped.append(s))
    fr = FlightRecorder(str(tmp_path / "f.jsonl"))
    fr.install(signals=True)
    try:
        signal.raise_signal(signal.SIGTERM)
        assert fr.dumps == 1
        assert stopped == [signal.SIGTERM], \
            "arming the recorder must not drop the graceful handler"
    finally:
        fr.uninstall()
        signal.signal(signal.SIGTERM, prev)
