"""The actor baseline must itself be a correct Game of Life — otherwise the
speedup comparison in BASELINE.md is against a broken strawman."""

import numpy as np
import pytest

from baselines.actor_gol import ActorGrid
from gameoflifewithactors_tpu.models import seeds
from gameoflifewithactors_tpu.models.rules import CONWAY
from gameoflifewithactors_tpu.ops.stencil import Topology

from .oracle import numpy_run


def test_actor_glider_matches_oracle():
    g = seeds.seeded((12, 12), "glider", 2, 2)
    sim = ActorGrid(g, workers=4)
    sim.run(8)
    got = sim.snapshot()
    sim.shutdown()
    np.testing.assert_array_equal(got, numpy_run(g, CONWAY, Topology.TORUS, 8))


def test_actor_random_soup_matches_oracle():
    rng = np.random.default_rng(1)
    g = rng.integers(0, 2, size=(10, 14), dtype=np.uint8)
    sim = ActorGrid(g, workers=3)
    pop = sim.run(5)
    got = sim.snapshot()
    sim.shutdown()
    want = numpy_run(g, CONWAY, Topology.TORUS, 5)
    np.testing.assert_array_equal(got, want)
    assert pop == int(want.sum())


def test_actor_dead_boundary():
    g = seeds.seeded((8, 8), "blinker", 3, 3)
    sim = ActorGrid(g, workers=2, torus=False)
    sim.run(2)
    got = sim.snapshot()
    sim.shutdown()
    np.testing.assert_array_equal(got, numpy_run(g, CONWAY, Topology.DEAD, 2))


# -- native C++ baseline ------------------------------------------------------

def _native():
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no g++ in image")
    from baselines import native_gol

    try:
        native_gol.build()
    except RuntimeError as e:
        pytest.skip(f"native build failed: {e}")
    return native_gol


@pytest.mark.parametrize("torus", [True, False])
def test_native_actor_matches_engine(torus):
    import jax.numpy as jnp

    from gameoflifewithactors_tpu.models.rules import CONWAY
    from gameoflifewithactors_tpu.ops.stencil import Topology, multi_step

    ng = _native()
    rng = np.random.default_rng(2)
    g = rng.integers(0, 2, size=(16, 32), dtype=np.uint8)
    want = np.asarray(multi_step(
        jnp.asarray(g), 6, rule=CONWAY,
        topology=Topology.TORUS if torus else Topology.DEAD))
    got, pop, _ = ng.run(g, 6, workers=4, torus=torus)
    np.testing.assert_array_equal(got, want)
    assert pop == int(want.sum())


def test_native_actor_highlife_rule_masks():
    import jax.numpy as jnp

    from gameoflifewithactors_tpu.models.rules import HIGHLIFE
    from gameoflifewithactors_tpu.ops.stencil import Topology, multi_step

    ng = _native()
    rng = np.random.default_rng(3)
    g = rng.integers(0, 2, size=(20, 20), dtype=np.uint8)
    want = np.asarray(multi_step(jnp.asarray(g), 4, rule=HIGHLIFE,
                                 topology=Topology.TORUS))
    got, _, _ = ng.run(g, 4, workers=2, rule="B36/S23")
    np.testing.assert_array_equal(got, want)
