"""Rule parsing + exhaustive rule-table correctness (SURVEY.md §5 'Unit')."""

import numpy as np
import pytest

from gameoflifewithactors_tpu.models.rules import (
    CONWAY,
    DAY_AND_NIGHT,
    HIGHLIFE,
    Rule,
    parse_rule,
)
from gameoflifewithactors_tpu.ops.stencil import apply_rule


def test_parse_bs_notation():
    r = parse_rule("B3/S23")
    assert r.born == frozenset({3}) and r.survive == frozenset({2, 3})
    assert parse_rule("b36/s23") == Rule(frozenset({3, 6}), frozenset({2, 3}), "HighLife")


def test_parse_classic_sb_notation():
    r = parse_rule("23/3")  # classic survival/birth order
    assert r.born == frozenset({3}) and r.survive == frozenset({2, 3})


def test_parse_named():
    assert parse_rule("conway") == CONWAY
    assert parse_rule("HighLife") == HIGHLIFE
    assert parse_rule("Day & Night") == DAY_AND_NIGHT
    assert parse_rule(CONWAY) is CONWAY


def test_parse_rejects_garbage():
    with pytest.raises(ValueError):
        parse_rule("B9/S23")
    with pytest.raises(ValueError):
        parse_rule("nonsense")


def test_parse_rejects_degenerate_typos():
    for bad in ("/", "23/", "/3", "B/S"):
        with pytest.raises(ValueError):
            parse_rule(bad)
    # but explicit lettered forms with one empty side are legitimate rules
    assert parse_rule("B2/S").born == frozenset({2})
    assert parse_rule("B/S23").survive == frozenset({2, 3})


def test_notation_roundtrip():
    for r in (CONWAY, HIGHLIFE, DAY_AND_NIGHT):
        assert parse_rule(r.notation) == r


def test_masks():
    assert CONWAY.birth_mask == 0b000001000
    assert CONWAY.survive_mask == 0b000001100


@pytest.mark.parametrize("rule", [CONWAY, HIGHLIFE, DAY_AND_NIGHT], ids=str)
def test_rule_table_exhaustive(rule):
    """All 2 states x 9 counts, vectorized apply_rule vs scalar oracle."""
    states = np.repeat(np.arange(2, dtype=np.uint8), 9).reshape(2, 9)
    counts = np.tile(np.arange(9, dtype=np.uint8), 2).reshape(2, 9)
    got = np.asarray(apply_rule(states, counts, rule))
    want = np.array(
        [[rule.next_state(int(s), int(c)) for s, c in zip(srow, crow)]
         for srow, crow in zip(states, counts)],
        dtype=np.uint8,
    )
    np.testing.assert_array_equal(got, want)


# -- goltpu-lint project rules (GOL009 lock-order, GOL010 metrics) ------------
#
# The lint engine's *project* rules reason across modules, so their
# fixtures live here as in-memory {path: source} sets fed through
# ``lint_sources`` — jax-free, like everything in analysis/lint.py.

import textwrap

from gameoflifewithactors_tpu.analysis.lint import lint_sources


def _lint(sources):
    return lint_sources({p: textwrap.dedent(s) for p, s in sources.items()})


def _codes(result, only=None):
    out = [f.code for f in result.findings]
    return [c for c in out if c == only] if only else out


_CYCLE_SRC = """
    import threading


    class Alpha:
        def __init__(self):
            self._lock = threading.Lock()
            self._beta = Beta()

        def tick(self):
            with self._lock:
                self._beta.poke()


    class Beta:
        def __init__(self):
            self._lock = threading.Lock()
            self._alpha = Alpha()

        def poke(self):
            with self._lock:
                self._alpha.tick()
"""


def test_gol009_positive_cross_class_cycle():
    res = _lint({"pkg/obs/pair.py": _CYCLE_SRC})
    msgs = [f.message for f in res.findings if f.code == "GOL009"]
    assert any("cycle" in m for m in msgs), msgs


def test_gol009_positive_plain_lock_reentry_is_self_deadlock():
    res = _lint({"pkg/obs/rec.py": """
        import threading


        class Recorder:
            def __init__(self):
                self._lock = threading.Lock()

            def add(self, x):
                with self._lock:
                    self._flush()

            def _flush(self):
                with self._lock:
                    pass
    """})
    msgs = [f.message for f in res.findings if f.code == "GOL009"]
    assert any("self-deadlock" in m for m in msgs), msgs


def test_gol009_negative_rlock_reentry_is_legal():
    res = _lint({"pkg/obs/rec.py": """
        import threading


        class Recorder:
            def __init__(self):
                self._lock = threading.RLock()

            def add(self, x):
                with self._lock:
                    self._flush()

            def _flush(self):
                with self._lock:
                    pass
    """})
    assert _codes(res, "GOL009") == []


def test_gol009_negative_call_into_lock_leaf_store():
    # SessionService -> SessionStore shape: the callee locks but never
    # calls out under its lock, so it cannot close a cycle today
    res = _lint({"pkg/serve/svc.py": """
        import threading


        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}

            def put(self, k, v):
                with self._lock:
                    self._d[k] = v


        class Service:
            def __init__(self):
                self._lock = threading.Lock()
                self._store = Store()

            def handle(self, k, v):
                with self._lock:
                    self._store.put(k, v)
    """})
    assert _codes(res, "GOL009") == []


def test_gol009_negative_out_of_scope_and_tests_exempt():
    # the same cycle shape outside obs/serve/resilience (and in tests/)
    # is not this rule's business
    res = _lint({"pkg/parallel/pair.py": _CYCLE_SRC,
                 "tests/test_pair.py": _CYCLE_SRC})
    assert _codes(res, "GOL009") == []


def test_gol010_positive_counter_without_total_suffix():
    res = _lint({"pkg/obs/m.py": """
        from .registry import REGISTRY

        REGISTRY.counter("cache_events", "cache hit/miss").inc()
    """})
    msgs = [f.message for f in res.findings if f.code == "GOL010"]
    assert len(msgs) == 1 and "_total" in msgs[0]


def test_gol010_positive_kind_conflict_across_files():
    res = _lint({
        "pkg/obs/a.py": """
            from .registry import REGISTRY

            REGISTRY.gauge("queue_depth", "admission queue").set(0)
        """,
        "pkg/serve/b.py": """
            from ..obs.registry import REGISTRY

            REGISTRY.histogram("queue_depth", "admission queue").observe(1)
        """,
    })
    msgs = [f.message for f in res.findings if f.code == "GOL010"]
    assert len(msgs) == 1 and "declared as" in msgs[0]


def test_gol010_positive_per_chip_gauge_missing_from_registry():
    res = _lint({
        "pkg/obs/aggregate.py": """
            PER_CHIP_GAUGES = ("mxu_duty_cycle",)
        """,
        "pkg/obs/dev.py": """
            from .registry import REGISTRY

            REGISTRY.gauge("hbm_used_ratio", "per-chip HBM").set(0.5)
        """,
    })
    msgs = [f.message for f in res.findings if f.code == "GOL010"]
    assert len(msgs) == 1 and "PER_CHIP_GAUGES" in msgs[0]


def test_gol010_negative_conventional_names_are_clean():
    res = _lint({
        "pkg/obs/aggregate.py": """
            PER_CHIP_GAUGES = ("mxu_duty_cycle", "hbm_used_ratio")
        """,
        "pkg/obs/m.py": """
            from .registry import REGISTRY

            REGISTRY.counter("cache_events_total", "cache hit/miss").inc()
            REGISTRY.gauge("hbm_used_ratio", "per-chip HBM").set(0.5)
            REGISTRY.gauge("sessions", "live sessions").set(3)
            REGISTRY.histogram("step_seconds", "tick wall time").observe(1)
        """,
    })
    assert _codes(res, "GOL010") == []


def test_gol010_negative_tests_and_unscanned_aggregate_exempt():
    res = _lint({
        # throwaway names in tests are the point there
        "tests/test_m.py": """
            from gameoflifewithactors_tpu.obs.registry import REGISTRY

            REGISTRY.counter("boom", "fixture").inc()
        """,
        # per-chip membership unknowable without obs/aggregate.py in the
        # scanned set: the suffix heuristic must stay quiet
        "pkg/obs/dev.py": """
            from .registry import REGISTRY

            REGISTRY.gauge("ici_busy_ratio", "per-chip ICI").set(0.1)
        """,
    })
    assert _codes(res, "GOL010") == []
