"""Rule parsing + exhaustive rule-table correctness (SURVEY.md §5 'Unit')."""

import numpy as np
import pytest

from gameoflifewithactors_tpu.models.rules import (
    CONWAY,
    DAY_AND_NIGHT,
    HIGHLIFE,
    Rule,
    parse_rule,
)
from gameoflifewithactors_tpu.ops.stencil import apply_rule


def test_parse_bs_notation():
    r = parse_rule("B3/S23")
    assert r.born == frozenset({3}) and r.survive == frozenset({2, 3})
    assert parse_rule("b36/s23") == Rule(frozenset({3, 6}), frozenset({2, 3}), "HighLife")


def test_parse_classic_sb_notation():
    r = parse_rule("23/3")  # classic survival/birth order
    assert r.born == frozenset({3}) and r.survive == frozenset({2, 3})


def test_parse_named():
    assert parse_rule("conway") == CONWAY
    assert parse_rule("HighLife") == HIGHLIFE
    assert parse_rule("Day & Night") == DAY_AND_NIGHT
    assert parse_rule(CONWAY) is CONWAY


def test_parse_rejects_garbage():
    with pytest.raises(ValueError):
        parse_rule("B9/S23")
    with pytest.raises(ValueError):
        parse_rule("nonsense")


def test_parse_rejects_degenerate_typos():
    for bad in ("/", "23/", "/3", "B/S"):
        with pytest.raises(ValueError):
            parse_rule(bad)
    # but explicit lettered forms with one empty side are legitimate rules
    assert parse_rule("B2/S").born == frozenset({2})
    assert parse_rule("B/S23").survive == frozenset({2, 3})


def test_notation_roundtrip():
    for r in (CONWAY, HIGHLIFE, DAY_AND_NIGHT):
        assert parse_rule(r.notation) == r


def test_masks():
    assert CONWAY.birth_mask == 0b000001000
    assert CONWAY.survive_mask == 0b000001100


@pytest.mark.parametrize("rule", [CONWAY, HIGHLIFE, DAY_AND_NIGHT], ids=str)
def test_rule_table_exhaustive(rule):
    """All 2 states x 9 counts, vectorized apply_rule vs scalar oracle."""
    states = np.repeat(np.arange(2, dtype=np.uint8), 9).reshape(2, 9)
    counts = np.tile(np.arange(9, dtype=np.uint8), 2).reshape(2, 9)
    got = np.asarray(apply_rule(states, counts, rule))
    want = np.array(
        [[rule.next_state(int(s), int(c)) for s, c in zip(srow, crow)]
         for srow, crow in zip(states, counts)],
        dtype=np.uint8,
    )
    np.testing.assert_array_equal(got, want)
