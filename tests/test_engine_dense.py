"""Golden-sequence + oracle tests for the dense stencil (SURVEY.md §5)."""

import jax
import numpy as np
import pytest

from gameoflifewithactors_tpu.models import seeds
from gameoflifewithactors_tpu.models.rules import CONWAY, DAY_AND_NIGHT, HIGHLIFE
from gameoflifewithactors_tpu.ops.stencil import Topology, multi_step, step

from .oracle import numpy_run


def run(state, n, rule=CONWAY, topology=Topology.TORUS):
    s = jax.numpy.asarray(state)
    for _ in range(n):
        s = step(s, rule=rule, topology=topology)
    return np.asarray(s)


def test_block_still_life():
    g = seeds.seeded((8, 8), "block", 3, 3)
    np.testing.assert_array_equal(run(g, 5), g)


def test_blinker_period_2():
    g = seeds.seeded((8, 8), "blinker", 3, 3)
    one = run(g, 1)
    assert not np.array_equal(one, g)
    np.testing.assert_array_equal(run(g, 2), g)


def test_glider_translates_1_1_every_4_gens():
    g = seeds.seeded((16, 16), "glider", 2, 2)
    out = run(g, 4)
    np.testing.assert_array_equal(out, np.roll(g, (1, 1), (0, 1)))


def test_glider_wraps_torus():
    g = seeds.seeded((8, 8), "glider", 5, 5)
    out = run(g, 4 * 8)  # 8 diagonal steps returns home on an 8x8 torus
    np.testing.assert_array_equal(out, g)


def test_glider_dies_at_dead_boundary():
    g = seeds.seeded((8, 8), "glider", 5, 5)
    out = run(g, 40, topology=Topology.DEAD)
    # A glider hitting a dead-boundary corner collapses to a 2x2 block.
    assert out.sum() == 4


def test_gosper_gun_emits_gliders():
    gun = seeds.pattern("gosper_gun")
    assert gun.sum() == 36
    g = seeds.seeded((80, 80), gun, 4, 4)
    out = run(g, 120, topology=Topology.DEAD)
    # Period-30 gun: after 120 gens, 4 gliders in flight (5 cells each).
    assert out.sum() == 36 + 4 * 5


@pytest.mark.parametrize("rule", [CONWAY, HIGHLIFE, DAY_AND_NIGHT], ids=str)
@pytest.mark.parametrize("topology", list(Topology), ids=lambda t: t.value)
def test_oracle_random_grids(rule, topology):
    rng = np.random.default_rng(42)
    g = rng.integers(0, 2, size=(33, 47), dtype=np.uint8)
    np.testing.assert_array_equal(
        run(g, 5, rule=rule, topology=topology),
        numpy_run(g, rule, topology, 5),
    )


def test_multi_step_matches_repeated_step():
    rng = np.random.default_rng(7)
    g = rng.integers(0, 2, size=(32, 32), dtype=np.uint8)
    got = np.asarray(multi_step(jax.numpy.asarray(g), 7, rule=CONWAY))
    np.testing.assert_array_equal(got, run(g, 7))


def test_multi_step_dynamic_n_no_recompile():
    g = jax.numpy.zeros((16, 16), dtype=jax.numpy.uint8)
    # n must stay a traced scalar operand (not a static arg), so different
    # generation counts share one executable.
    avals = multi_step.jitted.lower(g, 3, rule=CONWAY).in_avals
    assert any(a.shape == () and "int" in a.dtype.name for a in jax.tree.leaves(avals))
    multi_step(g, 5, rule=CONWAY)  # different n: must not need a new lowering


def test_auto_resolution_tpu_branches(monkeypatch):
    """_resolve_auto's TPU-side routing never runs in CI (tests force the
    CPU platform): pin it by faking the platform check. Resolution is
    pure — no native compile happens here."""
    from gameoflifewithactors_tpu import Engine
    from gameoflifewithactors_tpu.models.generations import parse_any
    from gameoflifewithactors_tpu.ops import pallas_stencil
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

    host = Engine(np.zeros((64, 64), np.uint8), "conway")  # CPU engine
    monkeypatch.setattr(pallas_stencil, "default_interpret", lambda: False)

    # 3x3 binary at the bench shape -> the native kernel
    assert host._resolve_auto(np.zeros((16384, 16384), np.uint8), None,
                              Topology.TORUS) == "pallas"
    # lane-unaligned width (% 128 words fails) -> packed SWAR
    assert host._resolve_auto(np.zeros((16384, 16000), np.uint8), None,
                              Topology.TORUS) == "packed"
    # word-unaligned width (% 32 fails): the early packed return
    assert host._resolve_auto(np.zeros((16384, 16010), np.uint8), None,
                              Topology.TORUS) == "packed"
    # (nx, 1) band mesh -> pallas for BOTH topologies (round-3 DEAD support)
    m = mesh_lib.make_mesh((8, 1))
    for topo in (Topology.TORUS, Topology.DEAD):
        assert host._resolve_auto(np.zeros((4096, 4096), np.uint8), m,
                                  topo) == "pallas"
    # 2D meshes flatten into nx*ny full-width bands -> pallas too
    # (VERDICT r3 Missing #4)
    m24 = mesh_lib.make_mesh((2, 4))
    assert host._resolve_auto(np.zeros((4096, 4096), np.uint8), m24,
                              Topology.TORUS) == "pallas"
    # ...but only when the flattened decomposition exists: height not
    # divisible into nx*ny bands -> packed
    assert host._resolve_auto(np.zeros((4100, 4096), np.uint8), m24,
                              Topology.TORUS) == "packed"
    # bands shorter than the exchange depth (4096/8 devices = 512-row
    # grid -> 64-row bands is fine; 64-row grid -> 8-row bands == g) still
    # band; a 32-row grid (4-row bands < g=8) cannot
    assert host._resolve_auto(np.zeros((32, 4096), np.uint8), m24,
                              Topology.TORUS) == "packed"

    # LtL on TPU: bit-sliced packed for binary (both neighborhoods);
    # multi-state decay routes from the on-chip ltl_planes record —
    # captured 2026-08-02 (planes 7.9e10 vs dense 6.7e9 cell-updates/s,
    # results/tpu_worklist.json), so auto picks the plane stack; absent
    # a usable capture it must stay dense (never route unmeasured)
    from gameoflifewithactors_tpu import engine as engine_mod

    bosco = Engine(np.zeros((64, 64), np.uint8), "bosco", backend="dense")
    assert bosco._resolve_auto(np.zeros((4096, 4096), np.uint8), None,
                               Topology.TORUS) == "packed"
    diamond = Engine(np.zeros((64, 64), np.uint8),
                     "R2,C0,M0,S6..11,B6..9,NN", backend="dense")
    assert diamond._resolve_auto(np.zeros((4096, 4096), np.uint8), None,
                                 Topology.TORUS) == "packed"
    multi = Engine(np.zeros((64, 64), np.uint8),
                   parse_any("R2,C4,M1,S3..8,B5..9"), backend="dense")
    monkeypatch.setattr(engine_mod, "_ltl_planes_tpu_rates",
                        lambda: {"planes": 7.9e10, "dense": 6.7e9})
    assert multi._resolve_auto(np.zeros((4096, 4096), np.uint8), None,
                               Topology.TORUS) == "packed"
    monkeypatch.setattr(engine_mod, "_ltl_planes_tpu_rates", lambda: None)
    assert multi._resolve_auto(np.zeros((4096, 4096), np.uint8), None,
                               Topology.TORUS) == "dense"
