"""Width-k ghost-zone pipeline (parallel/sharded.make_multi_step_packed_
ghost): ONE halo exchange per k generations on the 2D device mesh, with
the exchange issued before interior compute so XLA can overlap them.

The contracts under test:

- **bit-identity** — the pipeline equals the dense single-device oracle
  for k in {1, 2, 8}, TORUS and DEAD, on 1D-band and 2x2-mesh
  decompositions (corner traffic rides the two-phase parts exchange);
- **structural k× reduction** — an unrolled build performs exactly
  ``chunks`` collective exchanges where the lock-step build (k=1, same
  pipeline) performs ``k * chunks``, counted from compiled HLO
  (utils/profiling.collective_permute_count);
- **byte accounting** — ghost_exchange_bytes (the model the
  ``halo_bytes_total`` counter records) equals the compiled HLO's
  collective-permute bytes for one exchange;
- **guards** — a non-divisible grid is refused at placement and
  k > tile capacity at trace time, never clamped;
- **fleet plane** — the halo counters sum across processes while the
  per-chip overlap gauge refuses summation (obs/aggregate.py), and the
  2D shard index bounds of sharded checkpoints are validated
  (utils/checkpoint.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gameoflifewithactors_tpu.models.rules import CONWAY
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.packed import multi_step_packed
from gameoflifewithactors_tpu.ops.stencil import Topology
from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
from gameoflifewithactors_tpu.parallel import sharded
from gameoflifewithactors_tpu.utils.profiling import (
    collective_permute_bytes,
    collective_permute_count,
)


def _mesh(shape):
    return mesh_lib.make_mesh(shape, jax.devices()[: shape[0] * shape[1]])


def _soup(shape=(64, 128), seed=7):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 2, size=shape, dtype=np.uint8)


def _place(grid, m):
    return mesh_lib.device_put_sharded_grid(
        bitpack.pack(jnp.asarray(grid)), m)


class TestBitIdentity:
    """Pipeline output == dense single-device oracle, bit for bit."""

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD],
                             ids=lambda t: t.value)
    @pytest.mark.parametrize("k", [1, 2, 8])
    @pytest.mark.parametrize("mesh_shape", [(4, 1), (2, 2)],
                             ids=["band4x1", "mesh2x2"])
    def test_vs_single_device_oracle(self, mesh_shape, k, topology):
        grid = _soup()
        chunks = 2
        want = np.asarray(bitpack.unpack(multi_step_packed(
            bitpack.pack(jnp.asarray(grid)), chunks * k, rule=CONWAY,
            topology=topology)))
        m = _mesh(mesh_shape)
        run = sharded.make_multi_step_packed_ghost(
            m, CONWAY, topology, gens_per_exchange=k)
        got = np.asarray(bitpack.unpack(run(_place(grid, m), chunks)))
        np.testing.assert_array_equal(got, want)

    @pytest.mark.slow  # zero-row interior slices: pathological XLA CPU compile
    def test_boundary_tile_exactly_2k_rows(self):
        """h == 2k (empty interior slab) is the legal extreme: the tile
        is all boundary rings, and must still be exact."""
        m = _mesh((4, 1))  # (64, 128) -> 16-row tiles; k=8 -> 2k == 16
        grid = _soup()
        want = np.asarray(bitpack.unpack(multi_step_packed(
            bitpack.pack(jnp.asarray(grid)), 16, rule=CONWAY,
            topology=Topology.TORUS)))
        run = sharded.make_multi_step_packed_ghost(
            m, CONWAY, Topology.TORUS, gens_per_exchange=8)
        got = np.asarray(bitpack.unpack(run(_place(grid, m), 2)))
        np.testing.assert_array_equal(got, want)

    @pytest.mark.slow  # 33-gen block body: ~10 min of XLA CPU compile
    def test_deep_word_halo_lifts_32_gen_cap(self):
        """k > 32 needs a 2-word ghost zone per side — the regime the
        1-word deep runner refuses outright (its g <= 32 cap)."""
        m = _mesh((2, 1))  # (160, 256) -> (80, 8)-word tiles; k=33, hw=2
        with pytest.raises(ValueError, match=r"\[1, 32\]"):
            sharded.make_multi_step_packed_deep(m, CONWAY,
                                                gens_per_exchange=33)
        grid = _soup((160, 256))
        want = np.asarray(bitpack.unpack(multi_step_packed(
            bitpack.pack(jnp.asarray(grid)), 33, rule=CONWAY,
            topology=Topology.TORUS)))
        run = sharded.make_multi_step_packed_ghost(
            m, CONWAY, Topology.TORUS, gens_per_exchange=33)
        got = np.asarray(bitpack.unpack(run(_place(grid, m), 1)))
        np.testing.assert_array_equal(got, want)


class TestGuards:
    def test_rejects_k_below_one(self):
        m = _mesh((2, 2))
        with pytest.raises(ValueError, match="gens_per_exchange"):
            sharded.make_multi_step_packed_ghost(m, CONWAY,
                                                 gens_per_exchange=0)

    def test_refuses_k_exceeding_tile_at_trace_time(self):
        m = _mesh((4, 1))  # (64, 128) -> 16-row tiles: k=9 needs 18
        run = sharded.make_multi_step_packed_ghost(
            m, CONWAY, Topology.TORUS, gens_per_exchange=9)
        with pytest.raises(ValueError, match="needs a per-device tile"):
            run(_place(_soup(), m), 1)

    def test_refuses_halo_words_exceeding_tile(self):
        m = _mesh((1, 4))  # (64, 128) -> 1-word tiles: hw=1 needs 2
        run = sharded.make_multi_step_packed_ghost(
            m, CONWAY, Topology.TORUS, gens_per_exchange=2)
        with pytest.raises(ValueError, match="needs a per-device tile"):
            run(_place(_soup(), m), 1)

    def test_refuses_non_divisible_grid_at_placement(self):
        m = _mesh((4, 1))
        with pytest.raises(ValueError, match="not divisible"):
            _place(_soup((30, 128)), m)

    def test_refuses_zero_chunks(self):
        m = _mesh((2, 2))
        run = sharded.make_multi_step_packed_ghost(
            m, CONWAY, Topology.TORUS, gens_per_exchange=2)
        with pytest.raises(ValueError, match="chunks must be >= 1"):
            run(_place(_soup(), m), 0)

    def test_ghost_fits_and_best_mesh_shape(self):
        assert mesh_lib.ghost_halo_words(1) == 1
        assert mesh_lib.ghost_halo_words(32) == 1
        assert mesh_lib.ghost_halo_words(33) == 2
        assert mesh_lib.ghost_fits(16, 2, 8)
        assert not mesh_lib.ghost_fits(15, 2, 8)   # 2k > rows
        assert not mesh_lib.ghost_fits(64, 1, 8)   # 2hw > words
        assert not mesh_lib.ghost_fits(64, 4, 0)
        # (64 rows, 4 words) over 4 devices: 2x2 fits k=8; k=40 needs
        # 2 words of halo per side so only the (4, 1) band factorization
        # leaves wide-enough tiles
        assert mesh_lib.best_mesh_shape(4, 64, 4, gens_per_exchange=8) \
            == (2, 2)
        assert mesh_lib.best_mesh_shape(4, 320, 4, gens_per_exchange=40) \
            == (4, 1)
        assert mesh_lib.best_mesh_shape(4, 30, 4, gens_per_exchange=8) \
            is None
        # gens_per_exchange=0: lock-step divisibility only
        assert mesh_lib.best_mesh_shape(4, 64, 1, gens_per_exchange=0) \
            == (4, 1)


class TestCollectiveAccounting:
    """The k× exchange reduction and the byte model, proven from the
    HLO the compiler actually emits (CPU-runnable: structure, not
    wall-clock)."""

    def _count(self, run, p):
        return collective_permute_count(run.lower(p).compile().as_text())

    def test_exchange_count_reduced_exactly_k_times(self):
        m = _mesh((2, 2))
        k, chunks = 4, 3
        grid = _soup()
        p = _place(grid, m)
        ghost = sharded.make_multi_step_packed_ghost(
            m, CONWAY, Topology.TORUS, gens_per_exchange=k,
            unroll_chunks=chunks)
        # the lock-step comparator is the SAME pipeline at k=1 (one
        # exchange per generation) so XLA's collective-combining treats
        # both builds alike and the instruction ratio is exactly k
        lock = sharded.make_multi_step_packed_ghost(
            m, CONWAY, Topology.TORUS, gens_per_exchange=1,
            unroll_chunks=k * chunks)
        n_ghost = self._count(ghost, p)
        n_lock = self._count(lock, p)
        assert n_ghost > 0
        assert n_lock == k * n_ghost, (
            f"expected exactly {k}x fewer exchanges: lock-step emits "
            f"{n_lock} collective-permutes, ghost emits {n_ghost}")

    def test_modeled_bytes_match_compiled_hlo(self):
        for mesh_shape, k in [((2, 2), 4), ((4, 1), 8), ((2, 2), 1)]:
            m = _mesh(mesh_shape)
            p = _place(_soup(), m)
            run = sharded.make_multi_step_packed_ghost(
                m, CONWAY, Topology.TORUS, gens_per_exchange=k,
                unroll_chunks=1)  # one chunk == exactly one exchange
            measured = collective_permute_bytes(
                run.lower(p).compile().as_text())
            model = sharded.ghost_exchange_bytes(
                p.shape, m, Topology.TORUS, k)
            assert measured == model > 0, (
                f"mesh {mesh_shape}, k={k}: modeled {model} B/exchange "
                f"!= compiled {measured} B")

    def test_dead_topology_drops_wrap_sends(self):
        m = _mesh((2, 2))
        p = _place(_soup(), m)
        run = sharded.make_multi_step_packed_ghost(
            m, CONWAY, Topology.DEAD, gens_per_exchange=4,
            unroll_chunks=1)
        measured = collective_permute_bytes(
            run.lower(p).compile().as_text())
        model = sharded.ghost_exchange_bytes(p.shape, m, Topology.DEAD, 4)
        assert measured == model > 0
        torus = sharded.ghost_exchange_bytes(p.shape, m, Topology.TORUS, 4)
        assert model < torus  # no wrap traffic on DEAD edges

    def test_halo_counters_land_in_registry(self):
        from gameoflifewithactors_tpu.obs.registry import REGISTRY

        def value(name):
            fam = REGISTRY.snapshot().get(name) or {}
            return sum(s.get("value", 0.0)
                       for s in fam.get("series", []))

        m = _mesh((2, 2))
        k, chunks = 2, 3
        run = sharded.make_multi_step_packed_ghost(
            m, CONWAY, Topology.TORUS, gens_per_exchange=k)
        ex0, by0 = value("halo_exchanges_total"), value("halo_bytes_total")
        p = _place(_soup(), m)
        run(p, chunks)
        assert value("halo_exchanges_total") - ex0 == chunks
        per = sharded.ghost_exchange_bytes(
            (64, 4), m, Topology.TORUS, k)
        assert value("halo_bytes_total") - by0 == pytest.approx(
            chunks * per)
        snap = REGISTRY.snapshot()["halo_overlap_ratio"]
        ratio = snap["series"][0]["value"]
        assert 0.0 < ratio < 1.0


class TestFleetAggregation:
    """halo totals sum fleet-wide; the per-chip overlap gauge refuses."""

    def _exposition(self, **series):
        from gameoflifewithactors_tpu.obs.exporter import render_prometheus
        from gameoflifewithactors_tpu.obs.registry import MetricsRegistry

        reg = MetricsRegistry()
        for name, v in series.items():
            if name.endswith("_total"):
                reg.counter(name, "c").inc(v)
            else:
                reg.gauge(name, "g").set(v)
        return render_prometheus(reg.snapshot())

    def test_halo_totals_sum_overlap_gauge_refuses(self):
        from gameoflifewithactors_tpu.obs.aggregate import (
            PerChipSumError, sum_across_procs)

        per_proc = {
            "w0": self._exposition(halo_exchanges_total=3,
                                   halo_bytes_total=1024.0,
                                   halo_overlap_ratio=0.75),
            "w1": self._exposition(halo_exchanges_total=3,
                                   halo_bytes_total=2048.0,
                                   halo_overlap_ratio=0.5),
        }
        assert sum_across_procs(per_proc, "halo_exchanges_total") == 6.0
        assert sum_across_procs(per_proc, "halo_bytes_total") == 3072.0
        with pytest.raises(PerChipSumError, match="per-chip"):
            sum_across_procs(per_proc, "halo_overlap_ratio")


class TestEngineFacade:
    def test_engine_routes_to_ghost_pipeline(self):
        from gameoflifewithactors_tpu import Engine

        m = _mesh((2, 4))
        grid = _soup((64, 256))
        ref = Engine(grid, "conway", mesh=m)
        eng = Engine(grid, "conway", mesh=m, gens_per_exchange=8)
        assert eng._ghost_pipeline, "tile (32, 2) fits k=8 ghost zones"
        ref.step(19)
        eng.step(19)  # 2 ghost chunks + 3 per-gen remainder
        np.testing.assert_array_equal(eng.snapshot(), ref.snapshot())
        est = eng.halo_bytes_per_gen(source="model")
        assert 0 < est < ref.halo_bytes_per_gen(source="model")

    def test_engine_falls_back_to_deep_when_tile_too_small(self):
        from gameoflifewithactors_tpu import Engine

        m = _mesh((1, 8))  # 1-word tiles: 2hw > words, ghost refused
        eng = Engine(_soup((64, 256)), "conway", mesh=m,
                     gens_per_exchange=8)
        assert not eng._ghost_pipeline


class TestShardIndexBounds:
    """2D-mesh tiles shard BOTH axes of a sharded checkpoint; a
    re-tiling bug must fail loudly, not clamp (utils/checkpoint.py)."""

    def test_write_refuses_clamped_extent(self, tmp_path):
        from gameoflifewithactors_tpu.utils import checkpoint as ckpt

        data = np.zeros((4, 4), np.uint32)
        # [6, 10) clamps to [6, 8): 2 columns of data claimed as 4
        with pytest.raises(ckpt.CheckpointCorruptError, match="covers"):
            ckpt.write_shards(
                tmp_path, 0, [((slice(0, 4), slice(6, 10)), data)],
                global_shape=(4, 8), dtype=np.uint32)

    def test_write_refuses_rank_mismatch(self, tmp_path):
        from gameoflifewithactors_tpu.utils import checkpoint as ckpt

        data = np.zeros((4, 4), np.uint32)
        with pytest.raises(ckpt.CheckpointCorruptError, match="rank"):
            ckpt.write_shards(
                tmp_path, 0, [((slice(0, 4),), data)],
                global_shape=(4, 8), dtype=np.uint32)

    def test_verify_catches_out_of_bounds_manifest_index(self, tmp_path):
        import json

        from gameoflifewithactors_tpu.utils import checkpoint as ckpt

        shards = [((slice(0, 4), slice(0, 4)),
                   np.arange(16, dtype=np.uint32).reshape(4, 4)),
                  ((slice(0, 4), slice(4, 8)),
                   np.arange(16, 32, dtype=np.uint32).reshape(4, 4))]
        ckpt.write_shards(tmp_path, 0, shards,
                          global_shape=(4, 8), dtype=np.uint32)
        ckpt.commit_manifest(tmp_path, meta={}, num_processes=1)
        ckpt.verify_sharded(tmp_path)  # sane manifest passes
        mpath = tmp_path / "MANIFEST.json"
        manifest = json.loads(mpath.read_text())
        manifest["processes"][0]["shards"][1]["index"] = [[0, 4], [6, 10]]
        mpath.write_text(json.dumps(manifest))
        with pytest.raises(ckpt.CheckpointCorruptError,
                           match="out of bounds"):
            ckpt.verify_sharded(tmp_path)
