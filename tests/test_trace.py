"""Distributed trace propagation (obs/spans.py + serve/frontend.py).

The contract under test (README "Fleet observability"):

- a caller-supplied ``X-Goltpu-Trace`` header threads one trace id
  through frontend -> admission -> lane dispatch -> engine step, with
  an unbroken parent chain (every span's ``parent_id`` is another span
  of the same trace, or the caller's span id at the root);
- with no context bound, spans carry NO ids — the untraced hot path
  pays nothing and tapes stay byte-compatible with pre-trace dumps;
- trace binding is thread-local: concurrent requests with different
  trace ids never cross-contaminate each other's spans.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from gameoflifewithactors_tpu.obs import spans as obs_spans
from gameoflifewithactors_tpu.obs.spans import (
    TRACER,
    TraceContext,
    bind_trace,
    current_trace,
    new_span_id,
    new_trace_id,
    parse_trace_header,
    set_process_context,
)
from gameoflifewithactors_tpu.serve.frontend import TRACE_HEADER, SessionFrontend

from .test_serve import FILL, SPEC, _req, make_service

# -- unit: the context model --------------------------------------------------


def test_parse_trace_header_roundtrip_and_rejects():
    tid, sid = new_trace_id(), new_span_id()
    assert len(tid) == 32 and len(sid) == 16
    ctx = parse_trace_header(f"{tid}:{sid}")
    assert (ctx.trace_id, ctx.span_id) == (tid, sid)
    assert parse_trace_header(ctx.header()) == ctx
    root = parse_trace_header(tid)
    assert root.trace_id == tid and root.span_id is None
    for bad in ("", "xyz", tid[:-1], f"{tid}:{sid}x", f"{tid}:{sid}:extra",
                tid.upper()):
        with pytest.raises(ValueError):
            parse_trace_header(bad)


def test_untraced_spans_carry_no_ids():
    assert current_trace() is None
    with obs_spans.span("t.naked"):
        pass
    s = TRACER.last_completed()
    assert s.name == "t.naked"
    assert s.trace_id is None and s.span_id is None and s.parent_id is None
    assert "trace_id" not in s.to_dict()  # byte-compatible with old tapes


def test_bind_trace_assigns_ids_and_chains_parents():
    caller = TraceContext(new_trace_id(), new_span_id())
    with bind_trace(caller.trace_id, caller.span_id) as ctx:
        assert ctx.trace_id == caller.trace_id
        with obs_spans.span("t.outer"):
            with obs_spans.span("t.inner"):
                pass
    inner = [s for s in TRACER.spans() if s.name == "t.inner"][-1]
    outer = [s for s in TRACER.spans() if s.name == "t.outer"][-1]
    assert outer.trace_id == inner.trace_id == caller.trace_id
    assert outer.parent_id == caller.span_id  # root chains to the caller
    assert inner.parent_id == outer.span_id  # unbroken chain inside
    assert current_trace() is None  # binding restored on exit


def test_bind_trace_mints_when_caller_sent_nothing():
    with bind_trace() as ctx:
        assert len(ctx.trace_id) == 32
        with obs_spans.span("t.minted"):
            pass
    s = [s for s in TRACER.spans() if s.name == "t.minted"][-1]
    assert s.trace_id == ctx.trace_id and s.parent_id is None


def test_process_context_is_the_ambient_fallback():
    ctx = TraceContext(new_trace_id(), new_span_id())
    prev = set_process_context(ctx)
    try:
        assert current_trace() == ctx
        with obs_spans.span("t.ambient"):
            pass
        s = [s for s in TRACER.spans() if s.name == "t.ambient"][-1]
        assert s.trace_id == ctx.trace_id and s.parent_id == ctx.span_id
        # an explicit binding wins over the process-ambient context
        with bind_trace() as bound:
            assert current_trace().trace_id == bound.trace_id
    finally:
        set_process_context(prev)
    assert current_trace() is None


def test_child_env_roundtrip():
    ctx = TraceContext(new_trace_id(), new_span_id())
    env = ctx.child_env()
    assert parse_trace_header(env[obs_spans.TRACE_ENV_VAR]) == ctx


# -- HTTP: the serve chain ----------------------------------------------------


def _spans_of(trace_id, want_names, timeout_s=5.0):
    """Spans of one trace, polled until every wanted name landed (the
    response is sent from inside serve.request, so its span closes just
    after the client returns)."""
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        got = [s for s in TRACER.spans() if s.trace_id == trace_id]
        if want_names <= {s.name for s in got}:
            return got
        time.sleep(0.01)
    raise AssertionError(
        f"trace {trace_id[:8]} never completed {want_names}; "
        f"saw {[s.name for s in got]}")


def _assert_unbroken_chain(spans, caller_span_id):
    ids = {s.span_id for s in spans}
    for s in spans:
        assert s.span_id and s.parent_id, f"{s.name} missing ids"
        assert s.parent_id in ids or s.parent_id == caller_span_id, \
            f"{s.name} parent {s.parent_id} is neither a sibling span " \
            f"nor the caller"


def test_http_trace_threads_frontend_to_engine_step(tmp_path):
    svc, _reg = make_service()
    caller = TraceContext(new_trace_id(), new_span_id())
    with SessionFrontend(svc, 0) as fe:
        data = b'{"tenant": "acme", "spec": ' + \
            json.dumps(SPEC).encode() + \
            b', "fill": 0.35, "rng_seed": 7}'
        req = urllib.request.Request(
            f"http://127.0.0.1:{fe.port}/sessions", data=data,
            method="POST", headers={TRACE_HEADER: caller.header()})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
            assert r.headers[TRACE_HEADER] == caller.trace_id
            body = json.loads(r.read())
        assert body["trace_id"] == caller.trace_id
        sid = body["sid"]
        req = urllib.request.Request(
            f"http://127.0.0.1:{fe.port}/sessions/{sid}/step",
            data=b'{"n": 3}', method="POST",
            headers={TRACE_HEADER: caller.header()})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200

    spans = _spans_of(caller.trace_id,
                      {"serve.request", "serve.admission",
                       "lane.dispatch", "engine.step"})
    _assert_unbroken_chain(spans, caller.span_id)
    # the roots (one per request) chain to the caller's span id
    roots = [s for s in spans if s.name == "serve.request"]
    assert len(roots) == 2
    assert all(s.parent_id == caller.span_id for s in roots)
    # the leaf chains through the dispatch, not straight to the root
    step = [s for s in spans if s.name == "engine.step"][-1]
    dispatch = [s for s in spans if s.name == "lane.dispatch"][-1]
    assert step.parent_id == dispatch.span_id


def test_http_mints_trace_when_header_absent(tmp_path):
    svc, _reg = make_service()
    with SessionFrontend(svc, 0) as fe:
        code, body = _req(fe.port, "POST", "/sessions",
                          {"tenant": "t", "spec": SPEC, "fill": FILL})
        assert code == 201
        assert len(body["trace_id"]) == 32  # minted server-side


def test_http_rejects_garbled_trace_header(tmp_path):
    svc, _reg = make_service()
    with SessionFrontend(svc, 0) as fe:
        req = urllib.request.Request(
            f"http://127.0.0.1:{fe.port}/healthz",
            headers={TRACE_HEADER: "not-a-trace"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400


def test_concurrent_requests_never_cross_contaminate(tmp_path):
    svc, _reg = make_service()
    callers = [TraceContext(new_trace_id(), new_span_id())
               for _ in range(2)]
    with SessionFrontend(svc, 0) as fe:
        sids = []
        for i, c in enumerate(callers):
            code, body = _req(fe.port, "POST", "/sessions",
                              {"tenant": f"t{i}", "spec": SPEC,
                               "fill": FILL, "rng_seed": i})
            assert code == 201
            sids.append(body["sid"])

        errors = []
        barrier = threading.Barrier(len(callers))

        def hammer(caller, sid):
            try:
                barrier.wait(timeout=10)
                for _ in range(5):
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{fe.port}/sessions/{sid}/step",
                        data=b'{"n": 1}', method="POST",
                        headers={TRACE_HEADER: caller.header()})
                    with urllib.request.urlopen(req, timeout=10) as r:
                        got = json.loads(r.read())
                        if got["trace_id"] != caller.trace_id:
                            errors.append(
                                f"response for {caller.trace_id[:8]} "
                                f"claimed {got['trace_id'][:8]}")
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(repr(exc))

        threads = [threading.Thread(target=hammer, args=(c, s))
                   for c, s in zip(callers, sids)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors

    for caller in callers:
        spans = _spans_of(caller.trace_id,
                          {"serve.request", "lane.dispatch", "engine.step"})
        # every span of this trace chains within the trace: a single
        # foreign parent id would mean thread-local state leaked
        _assert_unbroken_chain(spans, caller.span_id)
        assert all(s.trace_id == caller.trace_id for s in spans)
