"""Elastic multi-host runtime: control plane, sharded checkpoints, and
fleet recovery (resilience/distributed.py, ISSUE 14).

The fast tests exercise the shared-filesystem control plane (heartbeats,
deadline-bounded barriers, peer monitors) and the sharded-v2 checkpoint
format in-process — every failure-detection promise is a unit here
("detected within the deadline" means an assertion on elapsed time, not
vibes). The slow test is the real thing: four OS processes forming a
multi-controller JAX fleet, one SIGKILLed mid-run, survivors
self-detecting in bounded time, the rebuilt fleet replaying from the
last verified checkpoint to a final grid bit-identical to a
single-device oracle. The full three-fault drill (kill + preempt +
checkpoint rot) lives in scripts/chaos_multihost.py and the
chaos-multihost-smoke CI job.
"""

import json
import os
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from gameoflifewithactors_tpu.resilience import distributed as D
from gameoflifewithactors_tpu.resilience.faultplan import FaultEvent
from gameoflifewithactors_tpu.utils import checkpoint as ckpt_lib
from gameoflifewithactors_tpu.utils import fault as fault_lib
from gameoflifewithactors_tpu.utils.checkpoint import CheckpointCorruptError


# -- heartbeats + peer monitor -------------------------------------------------

def test_heartbeat_beats_and_carries_generation(tmp_path):
    hb = D.Heartbeat(tmp_path, epoch=0, process_id=3,
                     interval_seconds=0.05).start()
    try:
        hb.set_generation(42)
        deadline = time.perf_counter() + 5.0
        while time.perf_counter() < deadline:
            payload = D.read_heartbeat(tmp_path, 0, 3)
            if payload and payload["generation"] == 42 and payload["seq"] >= 2:
                break
            time.sleep(0.02)
        assert payload["process_id"] == 3
        assert payload["generation"] == 42
        assert payload["seq"] >= 2  # the thread is beating, not just start()
    finally:
        hb.stop()


def test_peer_monitor_flags_dead_peer_within_deadline(tmp_path):
    """A peer that stops beating is declared lost in bounded time; a
    beating peer never is."""
    peer = D.Heartbeat(tmp_path, epoch=0, process_id=1,
                       interval_seconds=0.05).start()
    lost, lost_at = {}, []

    def on_lost(stale):
        lost.update(stale)
        lost_at.append(time.perf_counter())

    mon = D.PeerMonitor(tmp_path, epoch=0, process_id=0, num_processes=2,
                        deadline_seconds=0.5, on_peer_lost=on_lost,
                        poll_seconds=0.05).start()
    try:
        time.sleep(1.2)
        assert not lost  # beating peer stays alive past 2x the deadline
        peer.stop()  # "SIGKILL": the heartbeat file goes quiet
        t_dead = time.perf_counter()
        deadline = time.perf_counter() + 10.0
        while not lost and time.perf_counter() < deadline:
            time.sleep(0.02)
        assert set(lost) == {1}
        assert lost[1] >= 0.5  # measured staleness honors the deadline
        assert lost_at[0] - t_dead < 5.0  # detected in bounded time
    finally:
        mon.stop()
        peer.stop()


def test_peer_monitor_flags_peer_that_never_appeared(tmp_path):
    lost = {}
    mon = D.PeerMonitor(tmp_path, epoch=2, process_id=0, num_processes=2,
                        deadline_seconds=0.3, on_peer_lost=lost.update,
                        poll_seconds=0.05).start()
    try:
        deadline = time.perf_counter() + 10.0
        while not lost and time.perf_counter() < deadline:
            time.sleep(0.02)
        assert set(lost) == {1}
    finally:
        mon.stop()


# -- deadline-bounded barriers -------------------------------------------------

def test_barrier_completes_when_all_arrive(tmp_path):
    errs = []

    def arrive(pid):
        try:
            D.barrier(tmp_path, 0, "c0-pre", pid, 3, deadline_seconds=10.0)
        except Exception as exc:  # noqa: BLE001 — collected for the assert
            errs.append(exc)

    threads = [threading.Thread(target=arrive, args=(p,)) for p in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15.0)
    assert not errs
    assert not any(t.is_alive() for t in threads)


def test_barrier_deadline_bounds_the_wait(tmp_path):
    """A stalled peer must cost exactly the deadline, never a hang."""
    t0 = time.perf_counter()
    with pytest.raises(D.PeerLostError) as exc_info:
        D.barrier(tmp_path, 0, "c1-pre", 0, 2, deadline_seconds=0.5)
    elapsed = time.perf_counter() - t0
    assert 0.5 <= elapsed < 5.0
    assert exc_info.value.missing == (1,)  # the absentee is named


def test_barrier_fast_exits_on_terminal_peer(tmp_path):
    """A peer that already published a terminal status will never
    arrive — waiting out the full deadline would only slow recovery."""
    D.write_status(tmp_path, 0, 1, "error", 7, detail="boom")
    t0 = time.perf_counter()
    with pytest.raises(D.PeerLostError, match="terminal"):
        D.barrier(tmp_path, 0, "c2-pre", 0, 2, deadline_seconds=60.0)
    assert time.perf_counter() - t0 < 10.0  # nowhere near the 60s deadline


def test_preempt_flags_are_per_epoch(tmp_path):
    D.request_preempt(tmp_path, epoch=1, process_id=2)
    assert D.preempts_requested(tmp_path, 1, 4) == {2}
    assert D.preempts_requested(tmp_path, 2, 4) == set()


def test_elastic_spec_json_roundtrip():
    spec = D.ElasticSpec(shape=(32, 64), target_gens=50, chunk=10,
                         chunk_sleep_seconds=0.1)
    again = D.ElasticSpec.from_dict(json.loads(json.dumps(spec.to_dict())))
    assert again == spec
    assert isinstance(again.shape, tuple)


# -- sharded v2 checkpoints ----------------------------------------------------

def _write_two_process_generation(root, gen, arr):
    gd = ckpt_lib.generation_dir(root, gen)
    h = arr.shape[0] // 2
    ckpt_lib.write_shards(gd, 0, [((slice(0, h), slice(0, arr.shape[1])),
                                   arr[:h])],
                          global_shape=arr.shape, dtype=arr.dtype)
    ckpt_lib.write_shards(gd, 1, [((slice(h, arr.shape[0]),
                                    slice(0, arr.shape[1])), arr[h:])],
                          global_shape=arr.shape, dtype=arr.dtype)
    ckpt_lib.commit_manifest(gd, meta={"generation": gen},
                             num_processes=2)
    return gd


def test_sharded_roundtrip_verifies_and_falls_back(tmp_path):
    rng = np.random.default_rng(0)
    a10 = rng.integers(0, 2**32, size=(8, 4), dtype=np.uint32)
    a20 = rng.integers(0, 2**32, size=(8, 4), dtype=np.uint32)
    _write_two_process_generation(tmp_path, 10, a10)
    gd20 = _write_two_process_generation(tmp_path, 20, a20)

    out, meta, gdir, skipped = ckpt_lib.load_latest_verified(tmp_path)
    np.testing.assert_array_equal(out, a20)
    assert meta["generation"] == 20 and not skipped

    # flip bytes in one shard: verify refuses, restore falls back
    fault_lib.corrupt_checkpoint_file(gd20 / "shard-p0000.npz", seed=1)
    with pytest.raises(CheckpointCorruptError):
        ckpt_lib.verify_sharded(gd20)
    out, meta, gdir, skipped = ckpt_lib.load_latest_verified(tmp_path)
    np.testing.assert_array_equal(out, a10)
    assert meta["generation"] == 10
    assert [d.name for d, _why in skipped] == ["gen-00000020"]


def test_uncommitted_generation_is_invisible_to_restore(tmp_path):
    arr = np.ones((4, 4), np.uint32)
    _write_two_process_generation(tmp_path, 10, arr)
    # a torn generation: shards durable, manifest never committed
    gd = ckpt_lib.generation_dir(tmp_path, 20)
    ckpt_lib.write_shards(gd, 0, [((slice(0, 4), slice(0, 4)), arr * 2)],
                          global_shape=arr.shape, dtype=arr.dtype)
    out, meta, _gdir, skipped = ckpt_lib.load_latest_verified(tmp_path)
    assert meta["generation"] == 10  # the torn one was skipped
    assert "never" in skipped[0][1] or "MANIFEST" in skipped[0][1]


def test_commit_refuses_missing_sidecar_and_bad_cover(tmp_path):
    arr = np.zeros((4, 4), np.uint32)
    gd = ckpt_lib.generation_dir(tmp_path, 1)
    ckpt_lib.write_shards(gd, 0, [((slice(0, 2), slice(0, 4)), arr[:2])],
                          global_shape=arr.shape, dtype=arr.dtype)
    with pytest.raises(CheckpointCorruptError, match="missing"):
        ckpt_lib.commit_manifest(gd, meta={}, num_processes=2)
    # both sidecars present but jointly covering only half the array
    ckpt_lib.write_shards(gd, 1, [((slice(0, 2), slice(0, 4)), arr[:2])],
                          global_shape=arr.shape, dtype=arr.dtype)
    with pytest.raises(CheckpointCorruptError):
        ckpt_lib.commit_manifest(gd, meta={}, num_processes=2)


def test_prune_keeps_newest_committed_generations(tmp_path):
    arr = np.zeros((4, 4), np.uint32)
    for gen in (10, 20, 30, 40):
        _write_two_process_generation(tmp_path, gen, arr)
    removed = ckpt_lib.prune_sharded(tmp_path, keep=2)
    assert sorted(d.name for d in removed) == \
        ["gen-00000010", "gen-00000020"]
    assert [g for g, _d in ckpt_lib.list_generations(tmp_path)] == [30, 40]


# -- the real thing: kill one of four, recover bit-exact ----------------------

@pytest.mark.slow
def test_kill_one_of_four_recovers_bit_identical(tmp_path):
    """Four real processes, SIGKILL one mid-run: survivors self-detect
    within the deadline (no hang), the rebuilt fleet replays from the
    last verified sharded checkpoint, and the final grid is
    bit-identical to an unfaulted single-device oracle."""
    import axon_guard

    jax = axon_guard.force_cpu(1)
    import jax.numpy as jnp

    from gameoflifewithactors_tpu.models.generations import parse_any
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.ops.packed import multi_step_packed
    from gameoflifewithactors_tpu.ops.stencil import Topology

    spec = D.ElasticSpec(shape=(96, 64), target_gens=80, chunk=20,
                         chunk_sleep_seconds=0.25)
    env = {**os.environ}
    env["PYTHONPATH"] = axon_guard.strip_pythonpath() or \
        str(Path(__file__).resolve().parents[1])
    fleet = D.ElasticFleet(tmp_path / "run", spec, num_processes=4, env=env)
    report = fleet.run([FaultEvent(worker=1, at_gen=40,
                                   kind="process_kill")])

    assert report["ok"], json.dumps(report["epochs"], indent=2)
    assert [f["kind"] for f in report["faults_fired"]] == ["process_kill"]
    fault_epochs = [e for e in report["epochs"] if e["fired"]]
    bound = (spec.heartbeat_deadline_seconds
             + spec.barrier_deadline_seconds + 20.0)
    assert fault_epochs and fault_epochs[0]["detection_seconds"] <= bound
    # SIGKILLed worker is replaced, not shrunk: roster stays at 4
    assert all(e["num_processes"] == 4 for e in report["epochs"])
    # survivors exited on the distinct peer-lost status, nobody wedged
    codes = fault_epochs[0]["exit_codes"]
    assert D.EXIT_PEER_LOST in codes and None not in codes

    packed0 = jnp.asarray(bitpack.pack_np(D.initial_grid(spec)))
    oracle = bitpack.unpack_np(np.asarray(multi_step_packed(
        packed0, spec.target_gens, rule=parse_any(spec.rule),
        topology=Topology(spec.topology))))[:, :spec.shape[1]]
    final = np.load(report["final_grid"])
    np.testing.assert_array_equal(final, oracle)
    assert oracle.sum() > 0  # the universe is alive — the diff means something
