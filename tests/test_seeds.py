"""Seed library: plaintext/RLE parsing, placement, Bernoulli fill."""

import jax
import numpy as np
import pytest

from gameoflifewithactors_tpu.models import seeds


def test_from_plaintext():
    g = seeds.from_plaintext(".X.\n..X\nXXX")
    np.testing.assert_array_equal(g, [[0, 1, 0], [0, 0, 1], [1, 1, 1]])


def test_rle_decode_glider():
    g = seeds.from_rle("x = 3, y = 3, rule = B3/S23\nbob$2bo$3o!")
    np.testing.assert_array_equal(g, seeds.pattern("glider"))


def test_rle_decode_multiline_and_blank_rows():
    # 2$ skips a full row; runs of b at line end are implicit.
    g = seeds.from_rle("x = 2, y = 3\noo2$oo!")
    np.testing.assert_array_equal(g, [[1, 1], [0, 0], [1, 1]])


def test_rle_roundtrip():
    rng = np.random.default_rng(5)
    g = rng.integers(0, 2, size=(9, 14), dtype=np.uint8)
    np.testing.assert_array_equal(seeds.from_rle(seeds.to_rle(g)), g)


def test_place_bounds_check():
    with pytest.raises(ValueError):
        seeds.seeded((4, 4), "gosper_gun")


def test_patterns_registry():
    for name in ("block", "blinker", "glider", "gosper_gun", "pulsar"):
        assert seeds.pattern(name).sum() > 0
    with pytest.raises(KeyError):
        seeds.pattern("nope")


def test_bernoulli_fill():
    g = seeds.bernoulli(jax.random.key(0), (256, 256), p=0.5)
    frac = float(np.asarray(g).mean())
    assert 0.45 < frac < 0.55
    assert g.dtype == jax.numpy.uint8


def test_seeded_packed_matches_dense_seeding():
    from gameoflifewithactors_tpu.ops import bitpack

    dense = seeds.seeded((64, 128), "gosper_gun", 5, 32)  # col 32 = word 1
    packed = seeds.seeded_packed((64, 128), "gosper_gun", top=5, left_word=1)
    np.testing.assert_array_equal(packed, bitpack.pack_np(dense))


def test_seeded_packed_validates():
    with pytest.raises(ValueError, match="not a multiple"):
        seeds.seeded_packed((64, 100), "glider")
    with pytest.raises(ValueError, match="exceeds"):
        seeds.seeded_packed((8, 32), "gosper_gun")
