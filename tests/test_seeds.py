"""Seed library: plaintext/RLE parsing, placement, Bernoulli fill."""

import jax
import numpy as np
import pytest

from gameoflifewithactors_tpu.models import seeds


def test_from_plaintext():
    g = seeds.from_plaintext(".X.\n..X\nXXX")
    np.testing.assert_array_equal(g, [[0, 1, 0], [0, 0, 1], [1, 1, 1]])


def test_rle_decode_glider():
    g = seeds.from_rle("x = 3, y = 3, rule = B3/S23\nbob$2bo$3o!")
    np.testing.assert_array_equal(g, seeds.pattern("glider"))


def test_rle_decode_multiline_and_blank_rows():
    # 2$ skips a full row; runs of b at line end are implicit.
    g = seeds.from_rle("x = 2, y = 3\noo2$oo!")
    np.testing.assert_array_equal(g, [[1, 1], [0, 0], [1, 1]])


def test_rle_roundtrip():
    rng = np.random.default_rng(5)
    g = rng.integers(0, 2, size=(9, 14), dtype=np.uint8)
    np.testing.assert_array_equal(seeds.from_rle(seeds.to_rle(g)), g)


def test_place_bounds_check():
    with pytest.raises(ValueError):
        seeds.seeded((4, 4), "gosper_gun")


def test_patterns_registry():
    for name in ("block", "blinker", "glider", "gosper_gun", "pulsar"):
        assert seeds.pattern(name).sum() > 0
    with pytest.raises(KeyError):
        seeds.pattern("nope")


def test_bernoulli_fill():
    g = seeds.bernoulli(jax.random.key(0), (256, 256), p=0.5)
    frac = float(np.asarray(g).mean())
    assert 0.45 < frac < 0.55
    assert g.dtype == jax.numpy.uint8


def test_seeded_packed_matches_dense_seeding():
    from gameoflifewithactors_tpu.ops import bitpack

    dense = seeds.seeded((64, 128), "gosper_gun", 5, 32)  # col 32 = word 1
    packed = seeds.seeded_packed((64, 128), "gosper_gun", top=5, left_word=1)
    np.testing.assert_array_equal(packed, bitpack.pack_np(dense))


def test_seeded_packed_validates():
    with pytest.raises(ValueError, match="not a multiple"):
        seeds.seeded_packed((64, 100), "glider")
    with pytest.raises(ValueError, match="exceeds"):
        seeds.seeded_packed((8, 32), "gosper_gun")


def test_new_pattern_dynamics():
    """Diehard vanishes at exactly generation 130; pentadecathlon has
    period 15 — classic dynamics as correctness fixtures."""
    import jax.numpy as jnp

    from gameoflifewithactors_tpu.models.rules import CONWAY
    from gameoflifewithactors_tpu.ops.stencil import multi_step

    g = jnp.asarray(seeds.seeded((48, 48), "diehard", 20, 20))
    alive_129 = np.asarray(multi_step(g, 129, rule=CONWAY)).sum()
    alive_130 = np.asarray(multi_step(g, 130, rule=CONWAY)).sum()
    assert alive_129 > 0 and alive_130 == 0

    p = jnp.asarray(seeds.seeded((32, 32), "pentadecathlon", 10, 10))
    after = multi_step(p, 15, rule=CONWAY)
    np.testing.assert_array_equal(np.asarray(after), np.asarray(p))
    assert (np.asarray(multi_step(p, 7, rule=CONWAY)) != np.asarray(p)).any()


def test_save_ppm_round_trip(tmp_path):
    from gameoflifewithactors_tpu.utils.render import save_ppm

    g = np.array([[0, 1], [2, 3]], dtype=np.uint8)
    path = tmp_path / "frame.ppm"
    save_ppm(g, path, scale=2)
    data = path.read_bytes()
    assert data.startswith(b"P6\n4 4\n255\n")
    body = data.split(b"255\n", 1)[1]
    assert len(body) == 4 * 4 * 3
    # state 0 black, state 1 brightest
    pix = np.frombuffer(body, np.uint8).reshape(4, 4, 3)
    assert pix[0, 0, 0] == 0 and pix[0, 2, 0] == 255
    assert 0 < pix[2, 0, 0] < 255      # dying states grey out
    with pytest.raises(ValueError, match="2D"):
        save_ppm(np.zeros((2, 2, 2), np.uint8), tmp_path / "x.ppm")


def test_save_ppm_many_state_fade_distinct(tmp_path):
    # advisor round-2: integer 160 // top collapsed to a 0 step past 160
    # states, rendering every dying state alive-white; the float fade must
    # keep dying states below alive and monotonically darkening
    from gameoflifewithactors_tpu.utils.render import save_ppm

    states = np.arange(256, dtype=np.int32).reshape(16, 16)
    path = tmp_path / "fade.ppm"
    save_ppm(states, path)
    body = path.read_bytes().split(b"255\n", 1)[1]
    lum = np.frombuffer(body, np.uint8).reshape(16, 16, 3)[:, :, 0].ravel()
    assert lum[0] == 0 and lum[1] == 255          # dead black, alive white
    dying = lum[2:]
    assert dying.max() < 255                      # no dying state reads alive
    assert (np.diff(dying.astype(int)) <= 0).all()  # monotone fade
    assert dying.min() >= 95                      # still visible vs dead black


class TestExtendedRle:
    """Golly multi-state RLE (. / A..X / pA..yO tokens)."""

    def test_multistate_round_trip_random(self):
        rng = np.random.default_rng(9)
        grid = rng.integers(0, 5, size=(12, 31), dtype=np.uint8)
        text = seeds.to_rle(grid, rule="R2,C5,M1,S3..8,B5..9")
        assert "rule = R2,C5,M1,S3..8,B5..9" in text
        np.testing.assert_array_equal(seeds.from_rle(text), grid)

    def test_prefixed_states_round_trip(self):
        # states needing p..y prefixes: 24 (X), 25 (pA), 48 (pX), 49 (qA),
        # 255 (yO) — explicit states= since no rule string names 256 states
        grid = np.array([[0, 1, 24, 25], [48, 49, 254, 255]], dtype=np.uint8)
        text = seeds.to_rle(grid, rule="B3/S23")
        assert "pA" in text and "yO" in text
        np.testing.assert_array_equal(seeds.from_rle(text, states=256), grid)

    def test_golly_written_form_decodes(self):
        # the shape Golly writes for a Brian's Brain patch: dot for dead,
        # A/B for firing/dying, run counts on multi-char tokens
        text = ("x = 6, y = 2, rule = 2/3/3\n"
                "3.A2B$2.2A!\n")
        want = np.array([[0, 0, 0, 1, 2, 2],
                         [0, 0, 1, 1, 0, 0]], dtype=np.uint8)
        np.testing.assert_array_equal(seeds.from_rle(text), want)

    def test_binary_files_keep_legacy_reading(self):
        # uppercase B/O stay dead/alive when the rule is binary — the
        # extended letters only apply to multi-state headers
        text = "x = 3, y = 1, rule = B3/S23\nBOB!\n"
        np.testing.assert_array_equal(
            seeds.from_rle(text), np.array([[0, 1, 0]], dtype=np.uint8))

    def test_errors(self):
        import pytest

        with pytest.raises(ValueError, match="0..255"):
            seeds.to_rle(np.full((1, 1), 256, dtype=np.uint16))
        with pytest.raises(ValueError, match="prefix"):
            seeds.from_rle("x = 2, y = 1, rule = 2/3/3\npp!\n")
        with pytest.raises(ValueError, match="prefix"):
            seeds.from_rle("x = 2, y = 1, rule = 2/3/3\npb!\n")
