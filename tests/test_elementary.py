"""Elementary (Wolfram) 1D CA family: exhaustive oracle + known structure."""

import numpy as np
import jax.numpy as jnp
import pytest

from gameoflifewithactors_tpu.models.elementary import (
    RULE_90,
    RULE_110,
    ElementaryRule,
    parse_elementary,
)
from gameoflifewithactors_tpu.models.generations import parse_any
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.elementary import (
    evolve_spacetime,
    multi_step_elementary,
    step_elementary,
)
from gameoflifewithactors_tpu.ops.stencil import Topology


def _oracle(row: np.ndarray, rule: ElementaryRule, topology: Topology) -> np.ndarray:
    if topology is Topology.TORUS:
        left = np.roll(row, 1)
        right = np.roll(row, -1)
    else:
        left = np.concatenate([[0], row[:-1]])
        right = np.concatenate([row[1:], [0]])
    idx = (left << 2) | (row << 1) | right
    return ((rule.number >> idx) & 1).astype(np.uint8)


@pytest.mark.parametrize("topology", list(Topology), ids=lambda t: t.value)
def test_all_256_rules_match_oracle(topology):
    """One random row through every Wolfram rule vs the numpy oracle —
    the full rule table in one sweep (SURVEY.md §5 'unit: rule tables')."""
    rng = np.random.default_rng(7)
    row = rng.integers(0, 2, size=96, dtype=np.uint8)
    p = bitpack.pack(jnp.asarray(row[None]))
    for n in range(256):
        rule = ElementaryRule(n)
        got = np.asarray(bitpack.unpack(
            step_elementary(p, rule=rule, topology=topology)))[0]
        np.testing.assert_array_equal(got, _oracle(row, rule, topology),
                                      err_msg=f"rule {n}")


def test_rule_90_is_xor_and_sierpinski():
    # rule 90: next = left XOR right; a single cell grows the Sierpinski
    # triangle — row t has popcount 2^(ones in binary t)
    row = np.zeros(256, dtype=np.uint8)
    row[128] = 1
    # spacetime of one universe: (T+1, 1, Wp) -> squeeze -> (T+1, Wp),
    # which unpacks as a 2D image whose row t is generation t
    st = np.asarray(bitpack.unpack(evolve_spacetime(
        bitpack.pack(jnp.asarray(row[None])), 63, rule=RULE_90)[:, 0, :]))
    for t in (1, 2, 3, 4, 7, 15, 31, 63):
        assert st[t].sum() == 2 ** bin(t).count("1"), t


def test_rows_are_independent_universes():
    """An (H, Wp) array steps H separate 1D worlds: stacked == separate."""
    rng = np.random.default_rng(11)
    rows = rng.integers(0, 2, size=(4, 64), dtype=np.uint8)
    p = bitpack.pack(jnp.asarray(rows))
    got = np.asarray(bitpack.unpack(
        multi_step_elementary(p, 16, rule=RULE_110)))
    for i in range(4):
        want = rows[i]
        for _ in range(16):
            want = _oracle(want, RULE_110, Topology.TORUS)
        np.testing.assert_array_equal(got[i], want, err_msg=f"row {i}")


def test_parse_and_dispatch():
    assert parse_elementary("W110") == RULE_110
    assert parse_elementary("rule 90").number == 90
    assert parse_any("w30").number == 30
    assert parse_any("W110").notation == "W110"
    with pytest.raises(ValueError, match="0..255"):
        parse_elementary("W300")
    with pytest.raises(ValueError):
        parse_elementary("B3/S23")
    # 2D families still dispatch past the elementary matcher
    assert parse_any("B3/S23").notation == "B3/S23"


def test_spacetime_shape_and_initial_row():
    p = bitpack.pack(jnp.asarray(np.ones((1, 32), np.uint8)))
    st = evolve_spacetime(p, 5, rule=RULE_110)
    assert st.shape == (6, 1, 1)
    np.testing.assert_array_equal(np.asarray(st[0]), np.asarray(p))


def test_engine_rejects_1d_rules():
    from gameoflifewithactors_tpu import Engine

    with pytest.raises(ValueError, match="1D .*elementary.* rule"):
        Engine(np.zeros((8, 32), np.uint8), "W110")


# -- sharded 1D: context parallelism for the elementary family ----------------

class TestShardedElementary:
    """make_multi_step_elementary_sharded: rows = pure DP, width = CP with
    one halo word per side per chunk (creep absorbed by the 32-cell word
    for g <= 32); DEAD edge devices re-zero their exterior halo word every
    in-slab generation via the shared runtime edge code."""

    def _mesh(self, shape):
        import jax

        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        return mesh_lib.make_mesh(shape, jax.devices()[: shape[0] * shape[1]])

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    @pytest.mark.parametrize("mesh_shape,g", [
        ((1, 8), 1),
        ((2, 4), 8),
        ((4, 2), 32),   # the full creep budget of the halo word
        ((8, 1), 8),    # pure-DP degenerate: no width sharding at all
    ])
    def test_bit_identity_vs_single_device(self, mesh_shape, g, topology):
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
        from gameoflifewithactors_tpu.parallel import sharded

        rng = np.random.default_rng(23)
        m = self._mesh(mesh_shape)
        grid = rng.integers(0, 2, size=(8, 2048), dtype=np.uint8)
        p = bitpack.pack(jnp.asarray(grid))
        want = multi_step_elementary(p, 3 * g, rule=RULE_110,
                                     topology=topology)
        run = sharded.make_multi_step_elementary_sharded(
            m, RULE_110, topology, gens_per_exchange=g)
        got = run(mesh_lib.device_put_sharded_grid(p, m), 3)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_seam_crossing_signal(self):
        # W184 is the traffic rule: a lone car travels right forever and
        # must cross every shard seam and the global wrap intact
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
        from gameoflifewithactors_tpu.parallel import sharded

        m = self._mesh((1, 8))
        rule = parse_elementary("W184")
        grid = np.zeros((1, 512), np.uint8)
        grid[0, 3] = 1
        p = bitpack.pack(jnp.asarray(grid))
        run = sharded.make_multi_step_elementary_sharded(
            m, rule, Topology.TORUS, gens_per_exchange=16)
        got = np.asarray(bitpack.unpack(
            run(mesh_lib.device_put_sharded_grid(p, m), 40)))  # 640 gens
        want = np.zeros((1, 512), np.uint8)
        want[0, (3 + 640) % 512] = 1
        np.testing.assert_array_equal(got, want)

    def test_rejects_bad_exchange_depth(self):
        from gameoflifewithactors_tpu.parallel import sharded

        with pytest.raises(ValueError, match=r"\[1, 32\]"):
            sharded.make_multi_step_elementary_sharded(
                self._mesh((1, 8)), RULE_110, gens_per_exchange=33)
