"""Larger-than-Life: parser, log-tree stepper vs oracle, deep halos, engine."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gameoflifewithactors_tpu import Engine
from gameoflifewithactors_tpu.models.generations import parse_any
from gameoflifewithactors_tpu.models.ltl import BOSCO, MAJORITY, LtLRule, parse_ltl
from gameoflifewithactors_tpu.ops.ltl import multi_step_ltl, sliding_sum, step_ltl
from gameoflifewithactors_tpu.ops.stencil import Topology


def oracle(g: np.ndarray, rule: LtLRule, torus: bool, n: int) -> np.ndarray:
    """Plain-NumPy LtL reference (direct window sums, int arithmetic);
    honors rule.neighborhood — box ("M") or von Neumann diamond ("N")."""
    r = rule.radius
    g = g.astype(np.int32)
    for _ in range(n):
        p = np.pad(g, r, mode="wrap") if torus else np.pad(g, r)
        cnt = np.zeros_like(g)
        for dr in range(-r, r + 1):
            ac = r if rule.neighborhood == "M" else r - abs(dr)
            for dc in range(-ac, ac + 1):
                cnt += p[r + dr : p.shape[0] - r + dr, r + dc : p.shape[1] - r + dc]
        if not rule.middle:
            cnt -= g
        (b1, b2), (s1, s2) = rule.born, rule.survive
        born = (g == 0) & (cnt >= b1) & (cnt <= b2)
        keep = (g == 1) & (cnt >= s1) & (cnt <= s2)
        g = (born | keep).astype(np.int32)
    return g.astype(np.uint8)


# -- parsing ------------------------------------------------------------------

def test_parse_notation_and_names():
    assert parse_ltl("R5,C0,M1,S34..58,B34..45") == BOSCO
    assert parse_ltl("bosco") == BOSCO
    # internal whitespace is normalized for notation too, not just names
    assert parse_ltl("R5, C0, M1, S34..58, B34..45") == BOSCO
    assert parse_any("R5, C0, M1, S34..58, B34..45") == BOSCO
    assert BOSCO.notation == "R5,C0,M1,S34..58,B34..45"
    assert parse_any("bosco") == BOSCO
    assert isinstance(parse_any("R2,C0,M0,S3..8,B5..7"), LtLRule)
    # C3 parses now (multi-state LtL); C257 exceeds the uint8 cap
    for bad in ("R5,C0,M1,S34..58", "R0,C0,M1,S1..2,B1..2",
                "R8,C0,M1,S1..2,B1..2", "R5,C257,M1,S1..2,B1..2",
                "R2,C0,M1,S9..3,B1..2"):
        with pytest.raises(ValueError):
            parse_ltl(bad)
    assert parse_ltl("R5,C3,M1,S1..2,B1..2").states == 3


def test_radius1_m0_interval_reduces_to_life_like():
    """R1,M0,S2..3,B3..3 is exactly Conway: cross-check families."""
    from gameoflifewithactors_tpu.models.rules import CONWAY
    from gameoflifewithactors_tpu.ops.stencil import multi_step

    rule = parse_ltl("R1,C0,M0,S2..3,B3..3")
    rng = np.random.default_rng(0)
    g = rng.integers(0, 2, size=(20, 30), dtype=np.uint8)
    want = np.asarray(multi_step(jnp.asarray(g), 10, rule=CONWAY,
                                 topology=Topology.TORUS))
    got = np.asarray(multi_step_ltl(jnp.asarray(g), 10, rule=rule,
                                    topology=Topology.TORUS))
    np.testing.assert_array_equal(got, want)


# -- stepper vs oracle --------------------------------------------------------

@pytest.mark.parametrize("rule", [BOSCO, MAJORITY,
                                  parse_ltl("R2,C0,M0,S5..12,B7..10")], ids=str)
@pytest.mark.parametrize("topology", list(Topology), ids=lambda t: t.value)
def test_ltl_matches_oracle(rule, topology):
    rng = np.random.default_rng(11)
    g = rng.integers(0, 2, size=(40, 48), dtype=np.uint8)
    want = oracle(g, rule, topology is Topology.TORUS, 4)
    got = np.asarray(multi_step_ltl(jnp.asarray(g), 4, rule=rule,
                                    topology=topology))
    np.testing.assert_array_equal(got, want)


def test_bosco_long_run_matches_oracle():
    """40 generations of Bosco on a random soup — long enough for soup to
    condense into bugs, so any drift between the conv path's f32 counts
    and exact integer counts would accumulate and diverge."""
    rng = np.random.default_rng(0)
    g = (rng.random((96, 96)) < 0.45).astype(np.uint8)
    want = oracle(g, BOSCO, True, 40)
    got = np.asarray(multi_step_ltl(jnp.asarray(g), 40, rule=BOSCO,
                                    topology=Topology.TORUS))
    np.testing.assert_array_equal(got, want)
    assert got.sum() > 0  # this seed condenses into live bugs, not extinction


# -- sharded deep halos -------------------------------------------------------

@pytest.mark.parametrize("topology", list(Topology), ids=lambda t: t.value)
def test_ltl_sharded_bit_identity_deep_halo(topology):
    """Radius-5 halos cross tile boundaries 5 deep; the 2x4 mesh result
    must equal the single-device result exactly (corner blocks included)."""
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.make_mesh((2, 4), jax.devices())
    rng = np.random.default_rng(13)
    g = rng.integers(0, 2, size=(32, 64), dtype=np.uint8)
    single = Engine(g, BOSCO, topology=topology)
    meshed = Engine(g, BOSCO, topology=topology, mesh=m)
    single.step(6)
    meshed.step(6)
    np.testing.assert_array_equal(meshed.snapshot(), single.snapshot())


def test_engine_rejects_tiles_smaller_than_radius():
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.make_mesh((2, 4), jax.devices())
    with pytest.raises(ValueError, match="smaller than the rule radius"):
        Engine(np.zeros((8, 16), np.uint8), BOSCO, mesh=m)


# -- engine / checkpoint / cli ------------------------------------------------

def test_engine_checkpoint_roundtrip(tmp_path):
    from gameoflifewithactors_tpu.utils import checkpoint as ckpt

    rng = np.random.default_rng(5)
    g = rng.integers(0, 2, size=(32, 32), dtype=np.uint8)
    e = Engine(g, "bosco")
    e.step(3)
    e2 = ckpt.load_engine(ckpt.save(e, tmp_path / "ltl.npz"))
    assert e2.rule == BOSCO and e2.generation == 3
    np.testing.assert_array_equal(e2.snapshot(), e.snapshot())


def test_cli_ltl_end_to_end(capsys):
    from gameoflifewithactors_tpu.cli import main as cli_main

    rc = cli_main(["--grid", "32x32", "--rule", "bosco", "--seed", "random",
                   "--random-p", "0.4", "--steps", "3", "--render", "final",
                   "--population"])
    assert rc == 0
    assert "gen 3" in capsys.readouterr().out


def test_binary_rules_reject_multistate_grids():
    g = np.full((8, 32), 2, dtype=np.uint8)
    with pytest.raises(ValueError, match="binary"):
        Engine(g, "R1,C0,M0,S2..3,B3..3")
    with pytest.raises(ValueError, match="binary"):
        Engine(g, "B3/S23")
    e = Engine(np.zeros((8, 32), np.uint8), "bosco")
    with pytest.raises(ValueError, match="binary"):
        e.set_grid(g)


def test_checkpoint_version_stamp_per_layout(tmp_path):
    from gameoflifewithactors_tpu.utils import checkpoint as ckpt

    # packed engines write the v3 device layouts (no dense detour)
    e = Engine(np.zeros((8, 32), np.uint8), "B3/S23")
    meta = ckpt.load_grid(ckpt.save(e, tmp_path / "bin.npz"))[1]
    assert (meta["version"], meta["layout"]) == (3, "packed32")
    g = np.zeros((8, 32), np.uint8); g[2, 2] = 2
    e2 = Engine(g, "B2/S/C3")
    meta = ckpt.load_grid(ckpt.save(e2, tmp_path / "multi.npz"))[1]
    assert (meta["version"], meta["layout"]) == (3, "genplanes32")

    # byte-layout engines keep the historical stamps old readers expect
    e3 = Engine(np.zeros((8, 32), np.uint8), "B3/S23", backend="dense")
    assert ckpt.load_grid(ckpt.save(e3, tmp_path / "d1.npz"))[1]["version"] == 1
    e4 = Engine(g, "B2/S/C3", backend="dense")
    assert ckpt.load_grid(ckpt.save(e4, tmp_path / "d2.npz"))[1]["version"] == 2


@pytest.mark.parametrize("k", [1, 2, 3, 5, 7, 11, 15, 16, 31])
def test_sliding_sum_matches_direct(k):
    rng = np.random.default_rng(k)
    x = rng.integers(0, 9, size=(37, 41), dtype=np.int32)
    for axis in (0, 1):
        if k > x.shape[axis]:
            continue
        got = np.asarray(sliding_sum(jnp.asarray(x), k, axis=axis))
        n = x.shape[axis]
        want = sum(
            np.take(x, range(d, d + n - k + 1), axis=axis) for d in range(k)
        )
        np.testing.assert_array_equal(got, want)


def test_sliding_sum_full_width_and_bounds():
    x = jnp.asarray(np.arange(12, dtype=np.int32).reshape(3, 4))
    np.testing.assert_array_equal(
        np.asarray(sliding_sum(x, 4, axis=1)), np.asarray(x).sum(axis=1, keepdims=True))
    with pytest.raises(ValueError):
        sliding_sum(x, 5, axis=1)
    with pytest.raises(ValueError):
        sliding_sum(x, 0, axis=1)


class TestVonNeumann:
    """Diamond (|dx|+|dy| <= r) neighborhoods: Golly's NN field."""

    @pytest.mark.parametrize("r,m", [(1, True), (2, True), (3, False)])
    def test_matches_brute_force_oracle(self, r, m):
        rule = LtLRule(radius=r, born=(2, 4), survive=(3, min(6, 2 * r * (r + 1))),
                       middle=m, neighborhood="N")
        rng = np.random.default_rng(13)
        grid = rng.integers(0, 2, size=(18, 22), dtype=np.uint8)
        want = oracle(grid, rule, torus=True, n=1)
        got = np.asarray(multi_step_ltl(jnp.asarray(grid), 1, rule=rule,
                                        topology=Topology.TORUS))
        np.testing.assert_array_equal(got, want)

    def test_radius_1_diamond_is_von_neumann_gol(self):
        # R1 diamond, M0: the 4-neighbor von Neumann neighborhood
        rule = parse_ltl("R1,C0,M0,S1..2,B2..2,NN")
        assert rule.neighborhood == "N"
        assert rule.window_size == 5
        grid = np.zeros((8, 8), np.uint8)
        grid[3, 3] = grid[3, 4] = grid[4, 3] = 1  # L-tromino
        got = np.asarray(multi_step_ltl(jnp.asarray(grid), 1, rule=rule))
        np.testing.assert_array_equal(got, oracle(grid, rule, torus=True, n=1))

    def test_notation_round_trip_and_window(self):
        rule = parse_ltl("R3,C0,M1,S5..12,B6..9,NN")
        assert rule.notation == "R3,C0,M1,S5..12,B6..9,NN"
        assert parse_ltl(rule.notation) == rule
        assert rule.window_size == 2 * 3 * 4 + 1  # 25-cell diamond
        # Moore form stays suffix-free and unchanged
        assert parse_ltl("R3,C0,M1,S5..12,B6..9,NM").notation == \
            "R3,C0,M1,S5..12,B6..9"
        # interval cap uses the diamond size, not the box size
        with pytest.raises(ValueError, match="outside 0..25"):
            LtLRule(radius=3, born=(0, 30), survive=(1, 2), neighborhood="N")

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    @pytest.mark.parametrize("notation", [
        "R2,C0,M1,S2..6,B3..5,NN",
        "R1,C0,M0,S2..3,B2..2,NN",
        # slow: this container's XLA CPU takes >10 min inside ONE
        # backend_compile of the R4 diamond packed kernel (verified by a
        # faulthandler stack dump — compile, not deadlock), which blows
        # the tier-1 budget; R1/R2 keep the packed-diamond path covered
        # there, and full/TPU runs still exercise R4.
        # Re-examined for ISSUE 2 (2026-08-04) with the aot/ persistent
        # compile cache active: the COLD compile burned >21 CPU-minutes
        # on this 1-core host before being stopped unfinished, so the
        # cache (which only helps the SECOND run) cannot bring the param
        # under the 870 s tier-1 budget — local tier-1 is hermetically
        # cold by design (tests/conftest.py pins a fresh cache dir per
        # session), and a CI run that must first pay the >21 min cold
        # compile blows tier1.yml's 30-min job budget before its
        # actions/cache entry ever exists. The mark stays.
        pytest.param("R4,C0,M1,S10..22,B12..17,NN",
                     marks=pytest.mark.slow),
    ])
    def test_packed_diamond_matches_dense(self, notation, topology):
        """The packed path serves diamond rules now (per-row-separable
        sums): bit-identity against the dense prefix-sum path."""
        from gameoflifewithactors_tpu.ops import bitpack
        from gameoflifewithactors_tpu.ops.ltl import multi_step_ltl
        from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_packed

        rule = parse_ltl(notation)
        rng = np.random.default_rng(61)
        grid = rng.integers(0, 2, size=(48, 96), dtype=np.uint8)
        want = multi_step_ltl(jnp.asarray(grid), 6, rule=rule,
                              topology=topology)
        got = bitpack.unpack(multi_step_ltl_packed(
            jnp.asarray(bitpack.pack_np(grid)), 6, rule=rule,
            topology=topology))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_engine_and_sharded_dense_path(self):
        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        rule = parse_ltl("R2,C0,M1,S2..6,B3..5,NN")
        rng = np.random.default_rng(23)
        grid = rng.integers(0, 2, size=(32, 64), dtype=np.uint8)
        single = Engine(grid, rule)              # auto -> dense off-TPU
        assert single.backend == "dense"
        sharded_e = Engine(grid, rule, mesh=mesh_lib.make_mesh((2, 4)))
        single.step(6)
        sharded_e.step(6)
        np.testing.assert_array_equal(single.snapshot(), sharded_e.snapshot())


class TestMultiStateLtL:
    """Golly's C >= 3: Generations-style decay over LtL windows — only
    state 1 excites, births land on dead cells, failed survivors decay
    through 2..C-1. Dense path only (the packed layout is 1 bit/cell)."""

    @staticmethod
    def _oracle(grid, rule, n, wrap):
        g = np.asarray(grid).astype(np.int32)
        r = rule.radius
        for _ in range(n):
            alive = (g == 1).astype(np.int32)
            pad = (np.pad(alive, r, mode="wrap") if wrap
                   else np.pad(alive, r))
            H, W = g.shape
            counts = np.zeros_like(g)
            for dy in range(-r, r + 1):
                for dx in range(-r, r + 1):
                    if rule.neighborhood == "N" and abs(dy) + abs(dx) > r:
                        continue
                    counts += pad[r + dy:r + dy + H, r + dx:r + dx + W]
            if not rule.middle:
                counts -= alive
            (b1, b2), (s1, s2) = rule.born, rule.survive
            born = (g == 0) & (counts >= b1) & (counts <= b2)
            keep = (g == 1) & (counts >= s1) & (counts <= s2)
            nxt = np.where(g == 0, np.where(born, 1, 0),
                           np.where(keep, 1, (g + 1) % rule.states))
            g = nxt.astype(np.int32)
        return g.astype(np.uint8)

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    @pytest.mark.parametrize("notation", [
        "R2,C4,M1,S3..8,B5..9",
        "R3,C5,M0,S10..20,B14..19,NN",
        "R1,C3,M0,S2..3,B2..2",       # r=1 diamond-of-the-mind: brain-ish
    ])
    def test_dense_matches_oracle(self, notation, topology):
        from gameoflifewithactors_tpu.ops.ltl import multi_step_ltl

        rule = parse_ltl(notation)
        assert rule.states > 2 and rule.notation == notation.upper().replace(" ", "")
        rng = np.random.default_rng(73)
        grid = rng.integers(0, rule.states, size=(40, 56), dtype=np.uint8)
        want = self._oracle(grid, rule, 5, wrap=topology is Topology.TORUS)
        got = np.asarray(multi_step_ltl(jnp.asarray(grid), 5, rule=rule,
                                        topology=topology))
        np.testing.assert_array_equal(got, want)

    def test_engine_facade_and_gates(self):
        from gameoflifewithactors_tpu import Engine

        rule = parse_ltl("R2,C4,M1,S3..8,B5..9")
        rng = np.random.default_rng(79)
        grid = rng.integers(0, 4, size=(48, 64), dtype=np.uint8)
        e = Engine(grid, rule)       # auto -> packed planes (r=2 box on CPU)
        assert e.backend == "packed" and e._ltl_planes
        e.step(4)
        want = self._oracle(grid, rule, 4, wrap=True)
        np.testing.assert_array_equal(e.snapshot(), want)
        # population counts ONLY alive (state 1) cells
        assert e.population() == int((want == 1).sum())
        # state validation knows the rule's state count
        with pytest.raises(ValueError, match="states 0..3"):
            Engine(np.full((16, 32), 4, np.uint8), rule)
        # multi-state sparse rides the plane stack now — bit-identical
        sp = Engine(grid, rule, backend="sparse")
        assert sp._ltl_planes
        sp.step(4)
        np.testing.assert_array_equal(sp.snapshot(), want)
        from gameoflifewithactors_tpu.ops import bitpack
        from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_packed

        with pytest.raises(ValueError, match="1 bit/cell"):
            multi_step_ltl_packed(
                bitpack.pack(jnp.zeros((8, 32), jnp.uint8)), 1, rule=rule)

    def test_sharded_dense_multistate(self):
        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

        rule = parse_ltl("R2,C4,M1,S3..8,B5..9")
        rng = np.random.default_rng(83)
        grid = rng.integers(0, 4, size=(32, 64), dtype=np.uint8)
        single = Engine(grid, rule)
        sharded_e = Engine(grid, rule, mesh=mesh_lib.make_mesh((2, 4)))
        single.step(5)
        sharded_e.step(5)
        np.testing.assert_array_equal(single.snapshot(), sharded_e.snapshot())

    def test_notation_and_parse_roundtrip(self):
        r = parse_ltl("R2,C4,M1,S3..8,B5..9")
        assert r.states == 4 and parse_ltl(r.notation) == r
        # C0/C1/C2 all mean binary
        assert parse_ltl("R2,C1,M1,S3..8,B5..9").states == 2
        with pytest.raises(ValueError, match="2..256"):
            from gameoflifewithactors_tpu.models.ltl import LtLRule

            LtLRule(radius=2, born=(3, 5), survive=(3, 5), states=300)

    def test_states_256_ceiling_steps(self):
        # the uint8 ceiling: the decay increment must not overflow the
        # Python-scalar-vs-uint8 cast (review finding; shared decay_select)
        from gameoflifewithactors_tpu.ops.ltl import multi_step_ltl

        rule = parse_ltl("R1,C256,M0,S2..3,B3..3")
        grid = np.zeros((16, 16), np.uint8)
        grid[4, 4:7] = 1          # blinker-ish line; failures decay to 2
        grid[10, 10] = 255        # top dying state wraps to 0
        out = np.asarray(multi_step_ltl(jnp.asarray(grid), 1, rule=rule,
                                        topology=Topology.DEAD))
        assert out[10, 10] == 0
        assert out.max() <= 255


class TestHROTIntervalLists:
    """Golly HROT form: S/B as comma-separated values or ranges
    (``R2,C2,S6-9,B7-8``), no M token = outer-totalistic (M0); born and
    survive become tuples of disjoint intervals honored by every path."""

    def test_parse_and_notation(self):
        r = parse_ltl("R2,C2,S2,4-6,B5,7..8")
        assert r.survive_intervals == ((2, 2), (4, 6))
        assert r.born_intervals == ((5, 5), (7, 8))
        assert not r.middle and r.states == 2
        # canonical notation round-trips through the parser losslessly
        assert r.notation == "R2,C0,M0,S2..2,4..6,B5..5,7..8"
        assert parse_ltl(r.notation) == r
        # classic single-interval strings still canonicalize unchanged
        assert parse_ltl("bosco").notation == "R5,C0,M1,S34..58,B34..45"
        with pytest.raises(ValueError, match="sorted and disjoint"):
            parse_ltl("R2,C2,S4-6,2,B7")     # out of order
        with pytest.raises(ValueError, match="sorted and disjoint"):
            parse_ltl("R2,C2,S2-4,5-6,B7")   # adjacent: should be one range
        with pytest.raises(ValueError):
            parse_ltl("R2,C2,B7")            # missing S section

    def test_empty_survival_list_and_canonical_equality(self):
        from gameoflifewithactors_tpu.ops import bitpack
        from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_packed

        # Golly allows an empty list: nothing survives, only births happen
        r = parse_ltl("R1,C2,S,B1-8")
        assert r.survive_intervals == () and r.notation == "R1,C0,M0,S,B1..8"
        assert parse_ltl(r.notation) == r
        g = np.zeros((16, 32), np.uint8)
        g[8, 8] = 1
        out = np.asarray(multi_step_ltl(jnp.asarray(g), 1, rule=r,
                                        topology=Topology.DEAD))
        assert out[8, 8] == 0            # no survival interval at all
        assert out.sum() == 8            # the 8 neighbors birthed
        pk = np.asarray(bitpack.unpack(multi_step_ltl_packed(
            bitpack.pack(jnp.asarray(g)), 1, rule=r, topology=Topology.DEAD)))
        np.testing.assert_array_equal(pk, out)
        # construction forms canonicalize: 1-tuple == bare pair (review
        # finding — rule-keyed compile caches must not see two rules)
        a = LtLRule(radius=2, born=((3, 5),), survive=((2, 3),))
        b = LtLRule(radius=2, born=(3, 5), survive=(2, 3))
        assert a == b and hash(a) == hash(b)

    @staticmethod
    def _oracle(g, rule, n, wrap):
        import numpy as np

        r = rule.radius
        g = np.asarray(g).astype(np.int32)
        for _ in range(n):
            p = np.pad(g, r, mode="wrap") if wrap else np.pad(g, r)
            cnt = np.zeros_like(g)
            for dr in range(-r, r + 1):
                ac = r if rule.neighborhood == "M" else r - abs(dr)
                for dc in range(-ac, ac + 1):
                    cnt += p[r + dr:p.shape[0] - r + dr,
                             r + dc:p.shape[1] - r + dc]
            if not rule.middle:
                cnt -= g
            in_b = np.zeros_like(g, dtype=bool)
            for lo, hi in rule.born_intervals:
                in_b |= (cnt >= lo) & (cnt <= hi)
            in_s = np.zeros_like(g, dtype=bool)
            for lo, hi in rule.survive_intervals:
                in_s |= (cnt >= lo) & (cnt <= hi)
            g = (((g == 0) & in_b) | ((g == 1) & in_s)).astype(np.int32)
        return g.astype(np.uint8)

    @pytest.mark.parametrize("topology", list(Topology), ids=lambda t: t.value)
    @pytest.mark.parametrize("notation", [
        "R2,C2,S6-9,12-15,B7-8",
        "R3,C2,M1,S10..14,20..25,B14..19,NN",
    ])
    def test_dense_and_packed_match_oracle(self, notation, topology):
        from gameoflifewithactors_tpu.ops import bitpack
        from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_packed

        rule = parse_ltl(notation)
        rng = np.random.default_rng(89)
        g = rng.integers(0, 2, size=(40, 64), dtype=np.uint8)
        want = self._oracle(g, rule, 4, topology is Topology.TORUS)
        dense = np.asarray(multi_step_ltl(jnp.asarray(g), 4, rule=rule,
                                          topology=topology))
        np.testing.assert_array_equal(dense, want)
        packed = np.asarray(bitpack.unpack(multi_step_ltl_packed(
            bitpack.pack(jnp.asarray(g)), 4, rule=rule, topology=topology)))
        np.testing.assert_array_equal(packed, want)

    def test_engine_and_kernel_serve_interval_lists(self):
        from gameoflifewithactors_tpu import Engine
        from gameoflifewithactors_tpu.ops import bitpack
        from gameoflifewithactors_tpu.ops.pallas_stencil import (
            multi_step_ltl_pallas,
        )
        from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_packed

        rule = parse_ltl("R2,C2,S6-9,12-15,B7-8")
        rng = np.random.default_rng(97)
        g = rng.integers(0, 2, size=(64, 64), dtype=np.uint8)
        a = Engine(g, rule, backend="packed")
        b = Engine(g, rule, backend="dense")
        a.step(5)
        b.step(5)
        np.testing.assert_array_equal(a.snapshot(), b.snapshot())
        p = bitpack.pack(jnp.asarray(g))
        want = multi_step_ltl_packed(p, 4, rule=rule, topology=Topology.TORUS)
        got = multi_step_ltl_pallas(p, 4, rule=rule, topology=Topology.TORUS,
                                    interpret=True, block_rows=16,
                                    gens_per_call=2)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_auto_routes_multistate_by_measured_crossover():
    """C >= 3 auto routing on CPU follows the measured planes-vs-dense
    crossover: planes for diamonds and box radius <= 3, dense for box
    radius >= 4 (engine._resolve_auto cites the measurements)."""
    from gameoflifewithactors_tpu import Engine

    g4 = np.random.default_rng(5).integers(0, 4, size=(32, 64),
                                           dtype=np.uint8)
    assert Engine(g4, "R2,C4,M1,S3..8,B5..9").backend == "packed"
    assert Engine(g4, "R3,C4,M1,S10..20,B14..19").backend == "packed"
    assert Engine(g4, "R5,C4,M1,S34..58,B34..45").backend == "dense"
    assert Engine(g4, "R5,C4,M0,S20..40,B25..38,NN").backend == "packed"
    # width that cannot pack: planes unavailable, dense serves
    g_odd = np.random.default_rng(5).integers(0, 4, size=(32, 48),
                                              dtype=np.uint8)
    assert Engine(g_odd, "R2,C4,M1,S3..8,B5..9").backend == "dense"


def test_tpu_multistate_routing_follows_ltl_planes_evidence(monkeypatch):
    """On TPU, C >= 3 auto routing is decided by the on-chip ltl_planes
    capture (VERDICT r4 #5): no usable record -> dense (never route onto
    an unmeasured path); planes measured faster -> planes; dense measured
    faster -> dense. The envelope stays the CPU crossover's (diamond or
    box radius <= 3) — box radius >= 4 is dense regardless."""
    from gameoflifewithactors_tpu import Engine, engine
    from gameoflifewithactors_tpu.ops import pallas_stencil

    # simulate the TPU platform for routing only; the plane-stack path the
    # routing may then pick is plain XLA code and runs on CPU fine
    monkeypatch.setattr(pallas_stencil, "default_interpret", lambda: False)
    g4 = np.random.default_rng(5).integers(0, 4, size=(32, 64),
                                           dtype=np.uint8)

    def with_rates(rates):
        monkeypatch.setattr(engine, "_ltl_planes_tpu_rates", lambda: rates)

    with_rates(None)
    assert Engine(g4, "R2,C4,M1,S3..8,B5..9").backend == "dense"
    with_rates({"planes": 2.0e11, "dense": 1.0e11})
    assert Engine(g4, "R2,C4,M1,S3..8,B5..9").backend == "packed"
    assert Engine(g4, "R2,C4,M0,S6..11,B6..9,NN").backend == "packed"
    # outside the measured-crossover envelope: dense even when planes wins
    assert Engine(g4, "R5,C4,M1,S34..58,B34..45").backend == "dense"
    with_rates({"planes": 1.0e11, "dense": 2.0e11})
    assert Engine(g4, "R2,C4,M1,S3..8,B5..9").backend == "dense"


def test_ltl_planes_rates_loader_guards(tmp_path, monkeypatch):
    """The evidence loader refuses non-TPU and malformed records."""
    import json

    from gameoflifewithactors_tpu import engine
    from gameoflifewithactors_tpu.utils import provenance

    def load_with(record):
        (tmp_path / "results").mkdir(exist_ok=True)
        (tmp_path / "results" / "tpu_worklist.json").write_text(
            json.dumps({"ltl_planes": record}))
        monkeypatch.setattr(provenance, "repo_root", lambda: str(tmp_path))
        engine._ltl_planes_tpu_rates.cache_clear()
        try:
            return engine._ltl_planes_tpu_rates()
        finally:
            engine._ltl_planes_tpu_rates.cache_clear()

    good = {"ok": True, "platform": "tpu",
            "cell_updates_per_sec": {"planes": 2.0, "dense": 1.0}}
    assert load_with(good) == {"planes": 2.0, "dense": 1.0}
    assert load_with({**good, "platform": "cpu"}) is None
    assert load_with({**good, "ok": False}) is None
    assert load_with({**good, "cell_updates_per_sec": {"planes": 2.0}}) is None
    assert load_with({**good, "cell_updates_per_sec": "broken"}) is None
