"""Estimate vs compiler-measured halo traffic (VERDICT.md round-1 Weak #5).

``Engine.halo_bytes_per_gen`` is an arithmetic estimate; here it is checked
against ``measured_halo_bytes_per_gen``, which counts collective-permute
operand bytes × source→target pairs in the SPMD-partitioned HLO that XLA
actually compiled for one generation on the 8-fake-device mesh.
"""

import jax
import numpy as np
import pytest

from gameoflifewithactors_tpu.engine import Engine
from gameoflifewithactors_tpu.ops.stencil import Topology
from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
from gameoflifewithactors_tpu.utils.profiling import (
    collective_permute_bytes,
    measured_halo_bytes_per_gen,
)


def _mesh(shape):
    return mesh_lib.make_mesh(shape, jax.devices()[: shape[0] * shape[1]])


def _grid(h=128, w=256):
    return np.random.default_rng(0).integers(0, 2, size=(h, w), dtype=np.uint8)


CASES = [
    # (mesh shape, backend, rule, topology)
    ((2, 4), "packed", "B3/S23", Topology.TORUS),
    ((2, 4), "packed", "B3/S23", Topology.DEAD),
    ((4, 2), "packed", "B3/S23", Topology.TORUS),
    ((2, 4), "dense", "B3/S23", Topology.TORUS),
    ((2, 2), "dense", "B3/S23", Topology.DEAD),
    ((2, 4), "dense", "brain", Topology.TORUS),    # Generations, uint8 path
    ((2, 2), "dense", "R2,C0,M0,S3..8,B5..7", Topology.TORUS),  # LtL depth 2
    # the packed multi-rule layouts (bit-planes / bit-sliced bitboards)
    ((2, 4), "packed", "brain", Topology.TORUS),
    ((2, 2), "packed", "R2,C0,M0,S3..8,B5..7", Topology.TORUS),
    # multi-state LtL plane stack: r-row stacked strips, one halo word
    ((2, 2), "packed", "R2,C4,M1,S3..8,B5..9", Topology.TORUS),
    ((2, 4), "packed", "R2,C4,M1,S3..8,B5..9", Topology.DEAD),
    # size-1 mesh axes: XLA emits self-pair permutes for the wrap "send";
    # those are device-local copies the byte counter must skip (review
    # finding — the model deliberately counts 0 for a size-1 axis)
    ((8, 1), "packed", "B3/S23", Topology.TORUS),
    ((1, 8), "packed", "B3/S23", Topology.TORUS),
]


@pytest.mark.parametrize("shape,backend,rule,topology", CASES,
                         ids=lambda v: str(v).replace(" ", ""))
def test_estimate_matches_compiled_hlo(shape, backend, rule, topology):
    eng = Engine(_grid(), rule=rule, topology=topology, mesh=_mesh(shape),
                 backend=backend)
    est = eng.halo_bytes_per_gen(source="model")
    got = measured_halo_bytes_per_gen(eng)
    assert got > 0, "no collective-permute found in the compiled HLO"
    assert got == est, (
        f"halo estimate {est} B/gen != measured {got} B/gen "
        f"(mesh {shape}, {backend}, {rule}, {topology})")


def test_default_source_delegates_to_measured_hlo():
    """halo_bytes_per_gen() serves the HLO-derived figure by default
    (VERDICT r3 Weak #6), cached; 'model' stays available and agreeing."""
    eng = Engine(_grid(), rule="B3/S23", topology=Topology.TORUS,
                 mesh=_mesh((2, 4)), backend="packed")
    auto = eng.halo_bytes_per_gen()
    assert auto == eng._halo_hlo == measured_halo_bytes_per_gen(eng)
    assert auto == eng.halo_bytes_per_gen(source="measured")
    assert auto == eng.halo_bytes_per_gen(source="model")
    with pytest.raises(ValueError, match="source"):
        eng.halo_bytes_per_gen(source="hunch")
    assert Engine(_grid(64, 64), "B3/S23").halo_bytes_per_gen() == 0


def test_deep_engine_measures_amortized_chunk():
    """A communication-avoiding engine's measured figure lowers the
    depth-g chunk and amortizes /g — not the per-generation runner, which
    would overstate what the engine actually moves."""
    pergen = Engine(_grid(), rule="B3/S23", mesh=_mesh((2, 4)),
                    backend="packed")
    deep = Engine(_grid(), rule="B3/S23", mesh=_mesh((2, 4)),
                  backend="packed", gens_per_exchange=8)
    # source='measured' so a broken deep branch cannot hide behind auto's
    # silent model fallback (review finding)
    d_meas = deep.halo_bytes_per_gen(source="measured")
    assert d_meas == deep.halo_bytes_per_gen(source="model")
    assert 0 < d_meas < pergen.halo_bytes_per_gen()


@pytest.mark.parametrize("rule", [
    "B3/S23",
    "brain",                     # plane-stack tiled sparse
    "R2,C0,M0,S3..8,B5..7",      # radius-r binary LtL tiled sparse
    "R2,C4,M1,S3..8,B5..9",      # radius-r multi-state plane tiled sparse
])
def test_sharded_sparse_includes_flag_traffic(rule):
    eng = Engine(_grid(), rule=rule, topology=Topology.TORUS,
                 mesh=_mesh((2, 4)), backend="sparse")
    est = eng.halo_bytes_per_gen(source="model")
    got = measured_halo_bytes_per_gen(eng)
    assert got == est, f"sparse halo estimate {est} != measured {got}"


BAND_CASES = [
    # (mesh shape, rule, topology) — band engines on (nx, 1) AND flattened
    # 2D meshes, every family the kernel serves
    ((8, 1), "B3/S23", Topology.TORUS),
    ((2, 4), "B3/S23", Topology.TORUS),
    ((2, 4), "B3/S23", Topology.DEAD),
    ((2, 4), "brain", Topology.TORUS),
    ((4, 2), "R2,C0,M0,S3..8,B5..7", Topology.TORUS),
]


@pytest.mark.parametrize("shape,rule,topology", BAND_CASES,
                         ids=lambda v: str(v).replace(" ", ""))
def test_band_estimate_matches_compiled_hlo(shape, rule, topology):
    """Band engines amortize the depth-(r·g) chunk exchange to exactly the
    banded per-generation rate, so the estimate must equal the compiled
    HLO's collective-permute bytes for one banded generation — including
    on flattened 2D meshes (the figure the facade test defers to)."""
    eng = Engine(_grid(), rule=rule, topology=topology, mesh=_mesh(shape),
                 backend="pallas", gens_per_exchange=2)
    est = eng.halo_bytes_per_gen(source="model")
    got = measured_halo_bytes_per_gen(eng)
    assert got > 0, "no collective-permute found in the compiled HLO"
    assert got == est, (
        f"band halo estimate {est} B/gen != measured {got} B/gen "
        f"(mesh {shape}, {rule}, {topology})")


def test_ltl_band_estimate_matches_per_gen_rate():
    """The LtL band kernel ships r*g-deep strips once per chunk: amortized
    per generation that is exactly the per-gen runner's r rows (review
    finding: the estimate undercounted the band engine g-fold)."""
    m = _mesh((4, 1))
    g = np.zeros((96, 128), np.uint8)
    pergen = Engine(g, "R2,C0,M1,S9..16,B8..12", mesh=m, backend="packed")
    band = Engine(g, "R2,C0,M1,S9..16,B8..12", mesh=m, backend="pallas",
                  gens_per_exchange=2)
    assert (band.halo_bytes_per_gen(source="model")
            == pergen.halo_bytes_per_gen(source="model") > 0)
    # the Generations band twin amortizes to the per-gen plane rate too
    gp = Engine(g, "brain", mesh=m, backend="packed")
    gb = Engine(g, "brain", mesh=m, backend="pallas", gens_per_exchange=2)
    assert (gb.halo_bytes_per_gen(source="model")
            == gp.halo_bytes_per_gen(source="model") > 0)


def test_unsharded_engine_moves_nothing():
    eng = Engine(_grid(64, 64), rule="B3/S23")
    assert eng.halo_bytes_per_gen() == 0
    assert measured_halo_bytes_per_gen(eng) == 0


def test_parser_on_synthetic_hlo():
    txt = """
  %x = u32[4]{0} add(%p, %q)
  %cp.1 = u32[1,8]{1,0} collective-permute(%a), channel_id=1, source_target_pairs={{0,2},{2,0}}
  %cp.2 = (u8[3,66]{1,0}, u8[3,66]{1,0}, u32[], u32[]) collective-permute-start(%b), source_target_pairs={{1,3}}
  %done = u8[3,66]{1,0} collective-permute-done(%cp.2)
  %cp.3 = u32[2]{0} collective-permute(%c), source_target_pairs={{0,0},{1,1},{2,3}}
"""
    # cp.1: 32 B x 2 pairs; cp.2 (TPU async tuple form): operand element
    # 198 B x 1 pair counted once; cp.3: only the 2->3 pair counts (the
    # self-pairs are device-local copies); -done and the add contribute 0
    assert collective_permute_bytes(txt) == 32 * 2 + 198 + 8


def test_parser_rejects_unknown_dtype():
    txt = "%cp = f8e4m3[8]{0} collective-permute(%a), source_target_pairs={{0,1}}\n"
    with pytest.raises(ValueError, match="unlisted dtype"):
        collective_permute_bytes(txt)
