"""Perf smoke guardrail (SURVEY.md §5): catastrophic slowdowns, not tuning.

Floors are ~30x below values measured on the slowest rig this runs on
(single-vCPU CPU JAX), so they only trip on real regressions — e.g. the
packed step silently falling back to per-cell work, a donation bug
forcing full copies, or an accidental host round-trip per generation.
"""

import time

import jax.numpy as jnp
import numpy as np

from gameoflifewithactors_tpu.models.rules import CONWAY
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.packed import multi_step_packed
from gameoflifewithactors_tpu.ops.stencil import Topology, multi_step

# 1024: at 512 the dense path still fits caches and the packed advantage
# shrinks to ~1.3-1.6x under load; at 1024 it is ~4x and stable
SIDE = 1024
GENS = 100


def _rate(run, state) -> float:
    state = run(state, 10)  # compile + warm
    state.block_until_ready()
    # best-of-3 (bench.py's pattern): a background process landing on one
    # timed region must not flip the packed-vs-dense ratio on this shared
    # 1-vCPU host
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        state = run(state, GENS)
        state.block_until_ready()
        best = max(best, SIDE * SIDE * GENS / (time.perf_counter() - t0))
    return best


def test_packed_rate_floor_and_packing_advantage():
    rng = np.random.default_rng(0)
    g = rng.integers(0, 2, size=(SIDE, SIDE), dtype=np.uint8)

    packed_rate = _rate(
        lambda s, n: multi_step_packed(s, n, rule=CONWAY, topology=Topology.TORUS),
        bitpack.pack(jnp.asarray(g)),
    )
    # measured ~1.2e10 on the 1-vCPU CPU rig; 2e8 only trips on catastrophe
    assert packed_rate > 2e8, f"packed path collapsed: {packed_rate:.2e}/s"

    dense_rate = _rate(
        lambda s, n: multi_step(s, n, rule=CONWAY, topology=Topology.TORUS),
        jnp.asarray(g),
    )
    # bit-packing is the framework's stated lever (BASELINE.md): it must
    # actually win, with margin slack for a loaded machine
    assert packed_rate > 1.5 * dense_rate, (
        f"packed ({packed_rate:.2e}/s) lost its advantage over dense "
        f"({dense_rate:.2e}/s)"
    )
