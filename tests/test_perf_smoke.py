"""Perf smoke guardrail (SURVEY.md §5): catastrophic slowdowns, not tuning.

Floors are ~30x below values measured on the slowest rig this runs on
(single-vCPU CPU JAX), so they only trip on real regressions — e.g. the
packed step silently falling back to per-cell work, a donation bug
forcing full copies, or an accidental host round-trip per generation.
"""

import time

import jax.numpy as jnp
import numpy as np

from gameoflifewithactors_tpu.models.rules import CONWAY
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.packed import multi_step_packed
from gameoflifewithactors_tpu.ops.stencil import Topology, multi_step

# 1024: at 512 the dense path still fits caches and the packed advantage
# shrinks to ~1.3-1.6x under load; at 1024 it is ~4x and stable
SIDE = 1024
GENS = 100


def _rate(run, state) -> float:
    state = run(state, 10)  # compile + warm
    state.block_until_ready()
    # best-of-3 (bench.py's pattern): a background process landing on one
    # timed region must not flip the packed-vs-dense ratio on this shared
    # 1-vCPU host
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        state = run(state, GENS)
        state.block_until_ready()
        best = max(best, SIDE * SIDE * GENS / (time.perf_counter() - t0))
    return best


def test_packed_rate_floor_and_packing_advantage():
    rng = np.random.default_rng(0)
    g = rng.integers(0, 2, size=(SIDE, SIDE), dtype=np.uint8)

    packed_rate = _rate(
        lambda s, n: multi_step_packed(s, n, rule=CONWAY, topology=Topology.TORUS),
        bitpack.pack(jnp.asarray(g)),
    )
    # measured ~1.2e10 on the 1-vCPU CPU rig; 2e8 only trips on catastrophe
    assert packed_rate > 2e8, f"packed path collapsed: {packed_rate:.2e}/s"

    dense_rate = _rate(
        lambda s, n: multi_step(s, n, rule=CONWAY, topology=Topology.TORUS),
        jnp.asarray(g),
    )
    # bit-packing is the framework's stated lever (BASELINE.md): it must
    # actually win, with margin slack for a loaded machine
    assert packed_rate > 1.5 * dense_rate, (
        f"packed ({packed_rate:.2e}/s) lost its advantage over dense "
        f"({dense_rate:.2e}/s)"
    )


def test_family_rate_floors():
    """Same catastrophe-only floors for the other families' serving paths:
    Generations bit-planes (CPU-measured ~1.1e10/s at 1024²), dense-byte
    LtL (the CPU serving path for binary LtL, ~5e8/s for bosco r=5), and
    the sparse engine on the config-#5 gun shape (~4.8e3 gens/s at 8192²,
    floored at 8192² scaled down to 2048²)."""
    from gameoflifewithactors_tpu.models import seeds
    from gameoflifewithactors_tpu.models.generations import parse_any
    from gameoflifewithactors_tpu.ops.ltl import multi_step_ltl
    from gameoflifewithactors_tpu.ops.packed_generations import (
        multi_step_packed_generations,
        pack_generations_for,
    )
    from gameoflifewithactors_tpu.ops.sparse import SparseEngineState

    rng = np.random.default_rng(1)
    brain = parse_any("brain")
    g = rng.integers(0, 3, size=(SIDE, SIDE), dtype=np.uint8)
    planes_rate = _rate(
        lambda s, n: multi_step_packed_generations(
            s, n, rule=brain, topology=Topology.TORUS),
        pack_generations_for(jnp.asarray(g), brain))
    assert planes_rate > 2e8, f"Generations planes collapsed: {planes_rate:.2e}/s"

    bosco = parse_any("bosco")
    gl = rng.integers(0, 2, size=(SIDE, SIDE), dtype=np.uint8)
    ltl_rate = _rate(
        lambda s, n: multi_step_ltl(s, n, rule=bosco, topology=Topology.TORUS),
        jnp.asarray(gl))
    assert ltl_rate > 1e7, f"dense LtL collapsed: {ltl_rate:.2e}/s"

    side = 2048
    state = SparseEngineState(
        jnp.asarray(seeds.seeded_packed((side, side), "gosper_gun",
                                        side // 2, side // 64)), CONWAY)
    state.step(8)
    state.active_tiles()  # sync
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        state.step(GENS)
        state.active_tiles()
        best = max(best, GENS / (time.perf_counter() - t0))
    # measured ~4.6e3 gens/s on this rig at 8192²; 100/s = catastrophe
    assert best > 100, f"sparse engine collapsed: {best:.1f} gens/s"
