"""Device sampler + roofline attribution (obs/device.py).

The sampler is driven against a fake ``memory_stats`` backend (the
injectable seam) — deterministic, no device assumptions; one test runs
the real default backend to pin the CPU host-RSS fallback. Roofline
tests cover the fold of static XLA cost analysis with measured step
rates, the TPU peak-model fractions, and the no-invented-denominator
rule for unmodelled platforms.
"""

import threading
import time

import pytest

from gameoflifewithactors_tpu.obs import device as device_lib
from gameoflifewithactors_tpu.obs.device import DeviceSampler, roofline_section
from gameoflifewithactors_tpu.obs.registry import MetricsRegistry


def _fake_backend(samples=None):
    return lambda: samples if samples is not None else [
        {"device": "0", "platform": "tpu", "bytes_in_use": 1000,
         "peak_bytes_in_use": 2000, "bytes_limit": 16000},
        {"device": "1", "platform": "tpu", "bytes_in_use": 1100,
         "peak_bytes_in_use": 2100, "bytes_limit": 16000},
    ]


def test_sample_once_sets_gauges_per_device():
    reg = MetricsRegistry()
    s = DeviceSampler(registry=reg, backend=_fake_backend())
    stats = s.sample_once()
    assert len(stats) == 2 and s.samples == 1
    g = reg.gauge("hbm_bytes_in_use")
    assert g.value(device="0", platform="tpu") == 1000
    assert g.value(device="1", platform="tpu") == 1100
    assert reg.gauge("hbm_bytes_peak").value(device="0", platform="tpu") == 2000
    assert reg.gauge("hbm_bytes_limit").value(device="1", platform="tpu") == 16000
    assert reg.counter("device_samples").value() == 1
    # a later sample overwrites in place (gauges, not counters)
    s._backend = _fake_backend([{"device": "0", "platform": "tpu",
                                 "bytes_in_use": 5000}])
    s.sample_once()
    assert g.value(device="0", platform="tpu") == 5000


def test_sampler_survives_raising_backend():
    reg = MetricsRegistry()
    s = DeviceSampler(registry=reg,
                      backend=lambda: (_ for _ in ()).throw(
                          RuntimeError("wedged")))
    assert s.sample_once() == []  # no raise out of the sampler
    assert reg.counter("device_sample_errors").value(error="RuntimeError") == 1


def test_sampler_thread_polls_on_interval():
    reg = MetricsRegistry()
    calls = []
    done = threading.Event()

    def backend():
        calls.append(time.perf_counter())
        if len(calls) >= 3:
            done.set()
        return []

    with DeviceSampler(0.02, registry=reg, backend=backend) as s:
        assert done.wait(timeout=5.0), "3 polls within 5s at 20ms interval"
    n = len(calls)
    time.sleep(0.1)
    assert len(calls) == n, "stop() must stop the polling"
    assert s.samples >= 3


def test_interval_validation_and_env_default(monkeypatch):
    with pytest.raises(ValueError):
        DeviceSampler(0.0, backend=_fake_backend())
    monkeypatch.setenv(device_lib.ENV_POLL, "7.5")
    assert DeviceSampler(backend=_fake_backend()).interval == 7.5


def test_default_backend_cpu_falls_back_to_host_rss():
    """On backends without memory_stats (CPU), the sampler serves host
    process RSS labeled source=host_rss — the gauge exists (acceptance:
    goltpu_hbm_bytes_in_use-style on a CPU run) and is honest about
    what it measures."""
    reg = MetricsRegistry()
    s = DeviceSampler(registry=reg)  # real default_memory_backend
    stats = s.sample_once()
    assert stats, "local devices must yield at least one sample"
    rec = stats[0]
    if rec.get("source") == "host_rss":  # the CPU tier-1 path
        assert rec["bytes_in_use"] > 0
        labels = {"device": rec["device"], "platform": rec["platform"],
                  "source": "host_rss"}
        assert reg.gauge("hbm_bytes_in_use").value(**labels) > 0
    else:  # a real accelerator backend
        assert reg.gauge("hbm_bytes_in_use").value(
            device=rec["device"], platform=rec["platform"]) is not None


# -- roofline attribution -----------------------------------------------------


_STEPS = [
    {"generation": 8, "generations_stepped": 8, "wall_seconds": 2.0,
     "cell_updates_per_sec": 4e9},
    {"generation": 16, "generations_stepped": 8, "wall_seconds": 1.0,
     "cell_updates_per_sec": 8e9},
]
_COST = {"generations": 8, "flops": 8e6, "bytes_accessed": 4e6}


def test_roofline_folds_cost_with_measured_rate():
    sec = roofline_section(cost=_COST, step_records=_STEPS, platform="tpu")
    ca = sec["cost_analysis"]
    assert ca["flops_per_gen"] == 1e6 and ca["bytes_per_gen"] == 5e5
    assert ca["arithmetic_intensity"] == 2.0
    ach = sec["achieved"]
    assert ach["cell_updates_per_sec"] == 8e9  # best record wins
    # best record: 8e9 cell/s over 1s covering 8 gens -> 1e9 cells/gen;
    # 1e6 FLOPs/gen => 1e-3 FLOPs/cell => 8e6 FLOP/s
    assert ach["flops_per_sec"] == pytest.approx(8e6)
    assert ach["bytes_per_sec"] == pytest.approx(4e6)
    assert sec["peak_modelled"]["hbm_gbps"] == 820.0
    frac = sec["achieved_fraction"]
    assert frac["of_hbm_bandwidth"] == pytest.approx(4e6 / 820e9)
    assert frac["of_temporal_g8_ceiling"] == pytest.approx(8e9 / 2.6e13)


def test_roofline_unmodelled_platform_has_no_invented_peak():
    sec = roofline_section(cost=_COST, step_records=_STEPS, platform="cpu")
    assert sec["peak_modelled"] is None
    assert "achieved_fraction" not in sec
    # the summary renderer says so instead of dividing by a guess
    text = "\n".join(device_lib.summary_lines(sec))
    assert "no modelled peak" in text


def test_roofline_partial_inputs():
    assert roofline_section() is None
    cost_only = roofline_section(cost=_COST, platform="tpu")
    assert "achieved" not in cost_only
    assert cost_only["cost_analysis"]["flops_per_gen"] == 1e6
    rate_only = roofline_section(step_records=_STEPS, platform="tpu")
    assert "cost_analysis" not in rate_only
    assert rate_only["achieved"]["cell_updates_per_sec"] == 8e9
    assert "flops_per_sec" not in rate_only["achieved"]


def test_engine_cost_analysis_and_report_roofline():
    """The compiled-runner attribution end-to-end: XLA's own FLOPs/bytes
    for this engine's runner, folded into the telemetry session's
    RunReport roofline section."""
    from gameoflifewithactors_tpu.coordinator import GridCoordinator
    from gameoflifewithactors_tpu.obs.report import begin_run_telemetry

    coord = GridCoordinator((64, 64), "B3/S23", random_fill=0.4,
                            backend="packed")
    cost = coord.engine.runner_cost_analysis()
    assert cost and cost["flops"] > 0 and cost["bytes_accessed"] > 0
    assert cost["generations"] == 8
    assert coord.engine.runner_cost_analysis() is cost  # cached

    telem = begin_run_telemetry()
    telem.attach(coord)
    coord.run(8)
    rep = telem.finish(engine=coord.engine)
    roof = rep.roofline
    assert roof is not None
    assert roof["cost_analysis"]["flops_per_gen"] == \
        pytest.approx(cost["flops"] / 8)
    assert roof["achieved"]["cell_updates_per_sec"] > 0
    assert roof["platform"] == "cpu" and roof["peak_modelled"] is None
    # the human summary renders the section
    assert any("roofline" in line for line in rep.summary_lines())
