"""Property tests (SURVEY.md §5): algebraic invariants over random rules
and grids, via Hypothesis.

These catch classes of bug the golden-sequence tests cannot: a rule-table
transposition that happens to preserve the glider, a shift direction that
only shows on asymmetric rules, a packed-path carry bug on widths the
fixed tests never use. Example counts are kept modest because every new
(rule, shape) pair is a fresh XLA compile on the CPU test rig.
"""

import jax.numpy as jnp
import numpy as np
import pytest

# rigs without hypothesis (it is a dev-only dependency) skip this module
# instead of erroring the whole collection
hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from gameoflifewithactors_tpu.models.rules import Rule
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.packed import step_packed
from gameoflifewithactors_tpu.ops.stencil import Topology, step

# a compact universe of shapes: word-boundary-rich widths, odd heights
SHAPES = [(7, 32), (16, 64), (23, 96)]

rules = st.builds(
    Rule,
    born=st.frozensets(st.integers(0, 8), max_size=9),
    survive=st.frozensets(st.integers(0, 8), max_size=9),
)
shapes = st.sampled_from(SHAPES)
seeds_ = st.integers(0, 2**32 - 1)


def _grid(shape, seed):
    return np.random.default_rng(seed).integers(0, 2, size=shape, dtype=np.uint8)


def _dual(rule: Rule) -> Rule:
    """Complement duality: stepping the complemented grid under the dual
    rule complements the original step. B' = {8-k: k not in S},
    S' = {8-k: k not in B}."""
    return Rule(
        born=frozenset(8 - k for k in range(9) if k not in rule.survive),
        survive=frozenset(8 - k for k in range(9) if k not in rule.born),
    )


@settings(max_examples=12, deadline=None)
@given(rule=rules, shape=shapes, seed=seeds_)
def test_complement_duality_dense(rule, shape, seed):
    g = _grid(shape, seed)
    lhs = np.asarray(step(jnp.asarray(g), rule=rule, topology=Topology.TORUS))
    rhs = 1 - np.asarray(
        step(jnp.asarray(1 - g), rule=_dual(rule), topology=Topology.TORUS)
    )
    np.testing.assert_array_equal(lhs, rhs)


@settings(max_examples=12, deadline=None)
@given(rule=rules, shape=shapes, seed=seeds_,
       topology=st.sampled_from(list(Topology)))
def test_packed_matches_dense_random_rules(rule, shape, seed, topology):
    g = _grid(shape, seed)
    want = np.asarray(step(jnp.asarray(g), rule=rule, topology=topology))
    got = np.asarray(bitpack.unpack(
        step_packed(bitpack.pack(jnp.asarray(g)), rule=rule, topology=topology)))
    np.testing.assert_array_equal(got, want)


@settings(max_examples=10, deadline=None)
@given(rule=rules, shape=shapes, seed=seeds_,
       dy=st.integers(-5, 5), dx=st.integers(-33, 33))
def test_translation_equivariance_on_torus(rule, shape, seed, dy, dx):
    """roll(step(g)) == step(roll(g)) — the stencil must have no absolute
    position dependence, including across packed word boundaries (the
    packed path is asserted too: dx up to ±33 crosses word seams)."""
    g = _grid(shape, seed)
    a = np.roll(np.asarray(step(jnp.asarray(g), rule=rule, topology=Topology.TORUS)),
                (dy, dx), axis=(0, 1))
    b = np.asarray(step(jnp.asarray(np.roll(g, (dy, dx), axis=(0, 1))),
                        rule=rule, topology=Topology.TORUS))
    np.testing.assert_array_equal(a, b)
    bp = np.asarray(bitpack.unpack(step_packed(
        bitpack.pack(jnp.asarray(np.roll(g, (dy, dx), axis=(0, 1)))),
        rule=rule, topology=Topology.TORUS)))
    np.testing.assert_array_equal(bp, b)


@settings(max_examples=8, deadline=None)
@given(rule=rules, topology=st.sampled_from(list(Topology)))
def test_empty_grid_stays_empty_unless_b0(rule, topology):
    g = np.zeros((8, 32), dtype=np.uint8)
    out = np.asarray(step(jnp.asarray(g), rule=rule, topology=topology))
    if 0 in rule.born:
        # B0 on an empty torus births everywhere; DEAD boundary interior too
        assert out.sum() > 0
    else:
        assert out.sum() == 0


@settings(max_examples=8, deadline=None)
@given(rule=rules)
def test_full_torus_is_uniform(rule):
    """Every cell of a full torus has 8 live neighbors: the next grid is
    all-ones iff 8 is in the survive set, else all-zeros."""
    g = np.ones((8, 32), dtype=np.uint8)
    out = np.asarray(step(jnp.asarray(g), rule=rule, topology=Topology.TORUS))
    assert (out == (1 if 8 in rule.survive else 0)).all()


@settings(max_examples=20, deadline=None)
@given(shape=shapes, seed=seeds_)
def test_pack_roundtrip_random(shape, seed):
    g = _grid(shape, seed)
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack(bitpack.pack(jnp.asarray(g)))), g)
    np.testing.assert_array_equal(bitpack.pack_np(g),
                                  np.asarray(bitpack.pack(jnp.asarray(g))))


# -- multi-state LtL plane stack vs dense byte path ---------------------------

def _mk_multistate(radius, states, middle, hood, b_lo, b_w, s_lo, s_w):
    from gameoflifewithactors_tpu.models.ltl import LtLRule

    # intervals clamp to the rule's OWN window (the diamond's is smaller
    # than the box's); born avoids 0 (birth-from-nothing is a different
    # contract, rejected by the sparse paths)
    win = (2 * radius + 1) ** 2 if hood == "M" else 2 * radius * (radius + 1) + 1
    b_lo = min(max(1, b_lo), win)
    s_lo = min(s_lo, win)
    return LtLRule(radius=radius, states=states, middle=middle,
                   neighborhood=hood,
                   born=(b_lo, min(b_lo + b_w, win)),
                   survive=(s_lo, min(s_lo + s_w, win)))


_ltl_multistate = st.builds(
    _mk_multistate,
    radius=st.integers(1, 3),
    states=st.integers(3, 8),
    middle=st.booleans(),
    hood=st.sampled_from(["M", "N"]),
    b_lo=st.integers(1, 9), b_w=st.integers(0, 12),
    s_lo=st.integers(0, 9), s_w=st.integers(0, 12),
)


@settings(max_examples=12, deadline=None)
@given(rule=_ltl_multistate, seed=seeds_,
       topology=st.sampled_from(list(Topology)))
def test_ltl_planes_match_dense_for_random_multistate_rules(
        rule, seed, topology):
    """Any C >= 3 LtL rule: the bit-plane decay stepper must equal the
    dense byte path — random radii/state counts/interval positions reach
    comparator and carry-chain corners the fixed oracle rules never do."""
    from gameoflifewithactors_tpu.ops.ltl import multi_step_ltl
    from gameoflifewithactors_tpu.ops.packed_generations import (
        pack_generations_for,
        unpack_generations,
    )
    from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_planes

    grid = np.random.default_rng(seed).integers(
        0, rule.states, size=(24, 64), dtype=np.uint8)
    want = np.asarray(multi_step_ltl(
        jnp.asarray(grid), 3, rule=rule, topology=topology))
    got = np.asarray(unpack_generations(multi_step_ltl_planes(
        pack_generations_for(jnp.asarray(grid), rule), 3, rule=rule,
        topology=topology)))
    np.testing.assert_array_equal(got, want, err_msg=rule.notation)


# -- temporal-chunked sparse engine vs the packed oracle ----------------------

@settings(max_examples=8, deadline=None)
@given(rule=rules.filter(lambda r: 0 not in r.born),
       seed=seeds_, chunk=st.integers(2, 8), gens=st.integers(1, 19),
       topology=st.sampled_from(list(Topology)))
def test_chunked_sparse_matches_packed_random_rules(rule, seed, chunk,
                                                    gens, topology):
    """The temporally-chunked sparse engine (windows advance chunk
    generations per gather, per-step change detection for wake) is
    bit-identical to the packed oracle for RANDOM non-B0 rules, chunk
    depths, and generation counts — including n % chunk remainders and
    both boundary semantics. Generative cover for the fixed-case chunking
    suite in test_sparse.py."""
    from gameoflifewithactors_tpu.ops.packed import multi_step_packed
    from gameoflifewithactors_tpu.ops.sparse import SparseEngineState

    g = _grid((16, 64), seed)
    p = bitpack.pack(jnp.asarray(g))
    state = SparseEngineState(p, rule, topology=topology, chunk_gens=chunk,
                              tile_rows=8, tile_words=1)
    state.step(gens)
    want = multi_step_packed(p, gens, rule=rule, topology=topology)
    np.testing.assert_array_equal(np.asarray(state.packed), np.asarray(want))


# -- RLE round trip (incl. Golly extended multi-state tokens) -----------------

@settings(max_examples=25, deadline=None)
@given(seed=seeds_, h=st.integers(1, 20), w=st.integers(1, 40),
       states=st.sampled_from([2, 3, 5, 26, 200, 256]))
def test_rle_round_trip_random(seed, h, w, states):
    """to_rle/from_rle is the identity for random grids in every state
    range the format covers — binary b/o runs, A..X single letters, and
    p..y prefixed tokens — with the run-length and trailing-dead-cell
    compression in between."""
    from gameoflifewithactors_tpu.models import seeds as seeds_lib

    g = np.random.default_rng(seed).integers(0, states, size=(h, w),
                                             dtype=np.uint8)
    text = seeds_lib.to_rle(g)           # header rule is only a label here
    back = seeds_lib.from_rle(text, states=max(states, int(g.max()) + 1))
    np.testing.assert_array_equal(back, g)
