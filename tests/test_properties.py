"""Property tests (SURVEY.md §5): algebraic invariants over random rules
and grids, via Hypothesis.

These catch classes of bug the golden-sequence tests cannot: a rule-table
transposition that happens to preserve the glider, a shift direction that
only shows on asymmetric rules, a packed-path carry bug on widths the
fixed tests never use. Example counts are kept modest because every new
(rule, shape) pair is a fresh XLA compile on the CPU test rig.
"""

import jax.numpy as jnp
import numpy as np
from hypothesis import given, settings, strategies as st

from gameoflifewithactors_tpu.models.rules import Rule
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.packed import step_packed
from gameoflifewithactors_tpu.ops.stencil import Topology, step

# a compact universe of shapes: word-boundary-rich widths, odd heights
SHAPES = [(7, 32), (16, 64), (23, 96)]

rules = st.builds(
    Rule,
    born=st.frozensets(st.integers(0, 8), max_size=9),
    survive=st.frozensets(st.integers(0, 8), max_size=9),
)
shapes = st.sampled_from(SHAPES)
seeds_ = st.integers(0, 2**32 - 1)


def _grid(shape, seed):
    return np.random.default_rng(seed).integers(0, 2, size=shape, dtype=np.uint8)


def _dual(rule: Rule) -> Rule:
    """Complement duality: stepping the complemented grid under the dual
    rule complements the original step. B' = {8-k: k not in S},
    S' = {8-k: k not in B}."""
    return Rule(
        born=frozenset(8 - k for k in range(9) if k not in rule.survive),
        survive=frozenset(8 - k for k in range(9) if k not in rule.born),
    )


@settings(max_examples=12, deadline=None)
@given(rule=rules, shape=shapes, seed=seeds_)
def test_complement_duality_dense(rule, shape, seed):
    g = _grid(shape, seed)
    lhs = np.asarray(step(jnp.asarray(g), rule=rule, topology=Topology.TORUS))
    rhs = 1 - np.asarray(
        step(jnp.asarray(1 - g), rule=_dual(rule), topology=Topology.TORUS)
    )
    np.testing.assert_array_equal(lhs, rhs)


@settings(max_examples=12, deadline=None)
@given(rule=rules, shape=shapes, seed=seeds_,
       topology=st.sampled_from(list(Topology)))
def test_packed_matches_dense_random_rules(rule, shape, seed, topology):
    g = _grid(shape, seed)
    want = np.asarray(step(jnp.asarray(g), rule=rule, topology=topology))
    got = np.asarray(bitpack.unpack(
        step_packed(bitpack.pack(jnp.asarray(g)), rule=rule, topology=topology)))
    np.testing.assert_array_equal(got, want)


@settings(max_examples=10, deadline=None)
@given(rule=rules, shape=shapes, seed=seeds_,
       dy=st.integers(-5, 5), dx=st.integers(-33, 33))
def test_translation_equivariance_on_torus(rule, shape, seed, dy, dx):
    """roll(step(g)) == step(roll(g)) — the stencil must have no absolute
    position dependence, including across packed word boundaries (the
    packed path is asserted too: dx up to ±33 crosses word seams)."""
    g = _grid(shape, seed)
    a = np.roll(np.asarray(step(jnp.asarray(g), rule=rule, topology=Topology.TORUS)),
                (dy, dx), axis=(0, 1))
    b = np.asarray(step(jnp.asarray(np.roll(g, (dy, dx), axis=(0, 1))),
                        rule=rule, topology=Topology.TORUS))
    np.testing.assert_array_equal(a, b)
    bp = np.asarray(bitpack.unpack(step_packed(
        bitpack.pack(jnp.asarray(np.roll(g, (dy, dx), axis=(0, 1)))),
        rule=rule, topology=Topology.TORUS)))
    np.testing.assert_array_equal(bp, b)


@settings(max_examples=8, deadline=None)
@given(rule=rules, topology=st.sampled_from(list(Topology)))
def test_empty_grid_stays_empty_unless_b0(rule, topology):
    g = np.zeros((8, 32), dtype=np.uint8)
    out = np.asarray(step(jnp.asarray(g), rule=rule, topology=topology))
    if 0 in rule.born:
        # B0 on an empty torus births everywhere; DEAD boundary interior too
        assert out.sum() > 0
    else:
        assert out.sum() == 0


@settings(max_examples=8, deadline=None)
@given(rule=rules)
def test_full_torus_is_uniform(rule):
    """Every cell of a full torus has 8 live neighbors: the next grid is
    all-ones iff 8 is in the survive set, else all-zeros."""
    g = np.ones((8, 32), dtype=np.uint8)
    out = np.asarray(step(jnp.asarray(g), rule=rule, topology=Topology.TORUS))
    assert (out == (1 if 8 in rule.survive else 0)).all()


@settings(max_examples=20, deadline=None)
@given(shape=shapes, seed=seeds_)
def test_pack_roundtrip_random(shape, seed):
    g = _grid(shape, seed)
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack(bitpack.pack(jnp.asarray(g)))), g)
    np.testing.assert_array_equal(bitpack.pack_np(g),
                                  np.asarray(bitpack.pack(jnp.asarray(g))))
