"""Smoke tests: every example under examples/ runs end-to-end at toy sizes.

Examples are product surface — a migrating user's first contact — so they
stay green like any other code. Each runs in-process via its main(argv)
(same pattern as the CLI tests), on the 8-fake-CPU rig from conftest.py.
"""

def test_ensemble_runs(capsys):
    from examples.ensemble import main

    main(["--batch", "2", "--side", "64", "--gens", "8", "--report-every", "4"])
    out = capsys.readouterr().out
    assert "gen     8" in out and "density mean" in out


def test_checkpoint_resume_round_trip(capsys):
    from examples.checkpoint_resume import main

    main(["--side", "64", "--gens", "20"])
    assert "resumed == uninterrupted: True" in capsys.readouterr().out


def test_distributed_bands_both_layouts(capsys):
    from examples.distributed_bands import main

    # side must split into 32-cell words across the 2D mesh's 4 columns
    main(["--side", "256", "--gens", "4"])
    out = capsys.readouterr().out
    assert "2D tiles / SWAR" in out and "row bands / native kernel" in out


def test_sparse_gun_emits(capsys):
    from examples.sparse_gun import main

    main(["--side", "512", "--gens", "90", "--report-every", "90"])
    out = capsys.readouterr().out
    # after 90 gens the gun (36 cells) has emitted 3 gliders (5 cells each)
    assert "pop     51" in out


def test_wolfram_sierpinski(capsys):
    from examples.wolfram import main

    main(["--rule", "W90", "--width", "64", "--steps", "16"])
    out = capsys.readouterr().out
    assert "W90: 16 generations" in out
    # generation 16 of rule 90 has exactly 2 live cells (2^popcount(16))
    assert out.splitlines()[16].count("#") == 2


def test_ltl_zoo_runs(capsys):
    from examples.ltl_zoo import main

    main(["--side", "64", "--gens", "6"])
    out = capsys.readouterr().out
    assert out.count("pop") == 3 and "decay" in out


def test_long_row_runs(capsys):
    from examples.long_row import main

    main(["--cells", "2048", "--gens", "64", "--rules", "W30,W184"])
    out = capsys.readouterr().out
    assert "W30" in out and "W184" in out and "8 devices" in out


def test_fault_recovery_replays_bit_exact(capsys):
    from examples.fault_recovery import main

    main(["--side", "64", "--gens", "24", "--checkpoint-every", "4"])
    out = capsys.readouterr().out
    assert "dropped device shard" in out
    assert "final state bit-identical to the unfaulted run" in out


def test_telemetry_example_all_pillars(tmp_path, capsys):
    import json

    from examples.telemetry import main

    out = str(tmp_path / "rep.json")
    main(["--side", "64", "--gens", "8", "--ticks", "4", "--out", out,
          "--stall-demo"])
    text = capsys.readouterr().out
    assert "host phases" in text
    assert "last completed span:" in text  # the watchdog diagnostic fired
    rep = json.load(open(out))
    assert rep["phase_seconds"]["coordinator.tick"]["count"] == 4
    assert len(rep["step_metrics"]) == 4
    # the chrome-trace sibling for the perfetto overlay
    assert (tmp_path / "rep.trace.json").exists()
