"""Runtime-sanitizer tests (analysis/sanitizers.py): transfer-guard
scope wiring, the retrace budget over the compile-event log, and the
engine's GOLTPU_SANITIZE auto-wiring — including the retrace-regression
test that turns PR 2's warm-start attribution into an enforced
invariant: *a warm-started engine never pays a real XLA compile again*.

The transfer guard's teeth only bite where a real device→host transfer
happens (TPU/GPU); on this CPU rig jax performs no guarded transfer, so
those tests assert the *wiring* (guard config inside the scopes) — the
same scopes that trip on hardware.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from gameoflifewithactors_tpu.analysis import sanitizers
from gameoflifewithactors_tpu.aot import registry as aot_registry
from gameoflifewithactors_tpu.engine import Engine
from gameoflifewithactors_tpu.obs import compile as obs_compile


def _soup(shape=(64, 64), seed=7):
    return np.random.default_rng(seed).integers(
        0, 2, size=shape, dtype=np.uint8)


def _fake_event(kind="cache_miss", runner="fake_runner"):
    t1 = time.perf_counter()
    return obs_compile.CompileEvent(
        runner=runner, signature="u32[2,2]", wall_seconds=1.25,
        cache_miss=(kind == "cache_miss"), donated=False,
        t0=t1 - 1.25, t1=t1, kind=kind)


# -- enabled() gating ---------------------------------------------------------


def test_enabled_reads_env_per_call(monkeypatch):
    monkeypatch.delenv(sanitizers.ENV_SANITIZE, raising=False)
    assert not sanitizers.enabled()
    for on in ("1", "true", "ON", "yes"):
        monkeypatch.setenv(sanitizers.ENV_SANITIZE, on)
        assert sanitizers.enabled()
    monkeypatch.setenv(sanitizers.ENV_SANITIZE, "0")
    assert not sanitizers.enabled()


# -- transfer-guard scopes ----------------------------------------------------


def test_transfer_guard_scopes_set_jax_config(monkeypatch):
    import jax

    monkeypatch.setenv(sanitizers.ENV_SANITIZE, "1")
    assert jax.config.jax_transfer_guard_device_to_host in (None, "allow")
    with sanitizers.no_implicit_host_transfers():
        assert jax.config.jax_transfer_guard_device_to_host == "disallow"
        # a sanctioned readback re-opens the gate inside the guard
        with sanitizers.allow_host_transfers("declared readback"):
            assert jax.config.jax_transfer_guard_device_to_host == "allow"
        assert jax.config.jax_transfer_guard_device_to_host == "disallow"


def test_transfer_guard_scopes_are_noops_when_disabled(monkeypatch):
    import jax

    monkeypatch.delenv(sanitizers.ENV_SANITIZE, raising=False)
    with sanitizers.no_implicit_host_transfers():
        assert jax.config.jax_transfer_guard_device_to_host is None


def test_allow_scope_requires_a_reason():
    with pytest.raises(ValueError):
        with sanitizers.allow_host_transfers(""):
            pass


def test_engine_observe_surfaces_work_under_the_guard(monkeypatch):
    """snapshot/population/active_tiles carry their own allow-scopes, so
    the dense-engine tests (conftest wires this module pattern) keep
    working with the step loop guarded."""
    monkeypatch.setenv(sanitizers.ENV_SANITIZE, "1")
    eng = Engine(_soup(), "B3/S23", backend="packed")
    with sanitizers.no_implicit_host_transfers():
        eng.step(4)
        eng.block_until_ready()
        assert eng.snapshot().shape == (64, 64)
        assert eng.population() >= 0
        assert eng.active_tiles() is None


# -- retrace budget -----------------------------------------------------------


def test_retrace_budget_passes_on_hits_and_fails_on_misses():
    log = obs_compile.CompileEventLog()
    with sanitizers.retrace_budget(log=log) as sentinel:
        log.record(_fake_event("cache_hit"))
        log.record(_fake_event("aot_loaded"))
        assert sentinel.misses() == []
    with pytest.raises(sanitizers.RetraceError) as ei:
        with sanitizers.retrace_budget(log=log, context="unit"):
            log.record(_fake_event("cache_miss"))
    assert "fake_runner" in str(ei.value) and "unit" in str(ei.value)


def test_retrace_budget_allows_n_compiles():
    log = obs_compile.CompileEventLog()
    with sanitizers.retrace_budget(budget=2, log=log):
        log.record(_fake_event())
        log.record(_fake_event())


def test_retrace_budget_detaches_its_listener():
    log = obs_compile.CompileEventLog()
    with sanitizers.retrace_budget(log=log) as sentinel:
        pass
    log.record(_fake_event())
    assert sentinel.misses() == []  # disarmed: later misses are not ours


def test_retrace_budget_does_not_mask_body_exceptions():
    log = obs_compile.CompileEventLog()
    with pytest.raises(KeyError):
        with sanitizers.retrace_budget(log=log):
            log.record(_fake_event())
            raise KeyError("body failure wins over the budget check")


# -- the enforced warm-start invariant (satellite: retrace regression) --------


def test_warm_started_engine_steps_with_zero_cache_miss(cold_compile_cache):
    """PR 2 measured that a warm-started engine pays ~zero compile; this
    pins it as an *invariant*: warm the AOT/warm-start path, step, and
    assert zero ``cache_miss`` compile events via the CompileEventLog."""
    grid = _soup()
    cold = Engine(grid, "B3/S23", backend="packed")
    cold.step(2)
    cold.block_until_ready()
    aot_registry.serialize_engine(cold)

    warm = Engine(grid, "B3/S23", backend="packed")
    assert warm.aot_loaded, "the second engine must take the AOT path"
    before = len(obs_compile.COMPILE_LOG.events())
    with sanitizers.retrace_budget(context="warm-started engine"):
        warm.step(8)
        warm.block_until_ready()
        assert warm.population() >= 0
    after = obs_compile.COMPILE_LOG.events()[before:]
    assert [e for e in after if e.cache_miss] == [], \
        "a warmed engine recompiled — warm-start attribution regressed"


def test_engine_auto_arms_retrace_sentinel_under_sanitize(
        cold_compile_cache, monkeypatch):
    """GOLTPU_SANITIZE=1 + a warm-started engine = armed sentinel; a real
    compile landing after warm fails the very next step()."""
    monkeypatch.setenv(sanitizers.ENV_SANITIZE, "1")
    grid = _soup()
    cold = Engine(grid, "B3/S23", backend="packed")
    assert cold._retrace_sentinel is None  # cold engines may compile
    cold.step(1)
    cold.block_until_ready()
    aot_registry.serialize_engine(cold)

    warm = Engine(grid, "B3/S23", backend="packed")
    try:
        assert warm.aot_loaded and warm._retrace_sentinel is not None
        warm.step(2)  # clean: the AOT runner never re-traces
        obs_compile.COMPILE_LOG.record(_fake_event())  # simulated retrace
        with pytest.raises(sanitizers.RetraceError):
            warm.step(1)
    finally:
        warm._retrace_sentinel.disarm()  # never leak the listener


def test_engine_sentinel_absent_when_not_sanitizing(cold_compile_cache,
                                                    monkeypatch):
    monkeypatch.delenv(sanitizers.ENV_SANITIZE, raising=False)
    grid = _soup()
    cold = Engine(grid, "B3/S23", backend="packed")
    cold.step(1)
    cold.block_until_ready()
    aot_registry.serialize_engine(cold)
    warm = Engine(grid, "B3/S23", backend="packed")
    assert warm.aot_loaded and warm._retrace_sentinel is None
