"""Trivial pure-NumPy Game-of-Life oracle (SURVEY.md §5 'Oracle' row).

Deliberately naive and independent of the JAX code paths: np.pad + slice
sums + explicit per-cell rule membership. Used to cross-check the jitted
engines on random grids.
"""

import numpy as np

from gameoflifewithactors_tpu.models.rules import Rule
from gameoflifewithactors_tpu.ops.stencil import Topology


def numpy_step(state: np.ndarray, rule: Rule, topology: Topology) -> np.ndarray:
    mode = "wrap" if topology is Topology.TORUS else "constant"
    p = np.pad(state.astype(np.int32), 1, mode=mode)
    counts = sum(
        p[1 + dr : p.shape[0] - 1 + dr, 1 + dc : p.shape[1] - 1 + dc]
        for dr in (-1, 0, 1)
        for dc in (-1, 0, 1)
        if (dr, dc) != (0, 0)
    )
    out = np.zeros_like(state)
    for n in rule.born:
        out |= ((state == 0) & (counts == n)).astype(state.dtype)
    for n in rule.survive:
        out |= ((state == 1) & (counts == n)).astype(state.dtype)
    return out


def numpy_run(state: np.ndarray, rule: Rule, topology: Topology, n: int) -> np.ndarray:
    for _ in range(n):
        state = numpy_step(state, rule, topology)
    return state
