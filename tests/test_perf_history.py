"""scripts/perf_history.py: the append-only perf curve + anomaly scan.

Covers artifact folding from every shape the gate accepts (bench
records, BENCH_r* driver wrappers, tpu_best stores, RunReports),
append-only dedupe by content fingerprint (re-running never
duplicates), median/MAD anomaly detection with the MAD==0 fallback,
and the CLI contract: --check writes nothing, --strict turns
anomalies into exit 1, unusable input is exit 2. Runs the script as a
subprocess exactly as CI invokes it (stdlib-only, no package import).
"""

import importlib.util
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "scripts", "perf_history.py")

_spec = importlib.util.spec_from_file_location("perf_history", _SCRIPT)
ph = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(ph)


def _bench(value, metric="cell-updates/sec/chip, demo", at=None, **extra):
    rec = {"metric": metric, "value": value, "unit": "cell-updates/sec",
           **extra}
    if at:
        rec["recorded_at"] = at
    return rec


def _write(path, obj):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(obj, f)


def _run(args, cwd=None):
    return subprocess.run([sys.executable, _SCRIPT, *args],
                          capture_output=True, text=True, cwd=cwd or _REPO)


# -- shape extraction ---------------------------------------------------------


def test_extract_entries_every_known_shape():
    # plain bench record (+ the per-chip-equivalent companion series)
    es = ph.extract_entries(_bench(2e12, at="2026-01-01",
                                   single_chip_equivalent_updates_per_sec=5e11),
                            "results/a.json")
    assert {e["series"] for e in es} == {
        "cell-updates/sec/chip, demo",
        "cell-updates/sec/chip, demo [per-chip-equivalent]"}
    # BENCH_rNN driver wrapper: measurement under "parsed"
    es = ph.extract_entries({"n": 1, "cmd": ["x"], "rc": 0,
                             "parsed": _bench(1e12)}, "BENCH_r01.json")
    assert len(es) == 1 and es[0]["value"] == 1e12
    # a store: one entry per persisted key
    es = ph.extract_entries({"k1": _bench(1e12), "k2": _bench(2e12),
                             "note": "not a record"}, "results/tpu_best.json")
    assert sorted(e["value"] for e in es) == [1e12, 2e12]
    assert es[0]["source"].startswith("results/tpu_best.json#")
    # a RunReport: best cell-updates/sec across step metrics
    rep = {"step_metrics": [{"cell_updates_per_sec": 3e8},
                            {"cell_updates_per_sec": 5e8}],
           "created_at": "2026-01-02"}
    es = ph.extract_entries(rep, "results/tier1_cpu_report.json")
    assert len(es) == 1 and es[0]["value"] == 5e8
    assert es[0]["series"] == \
        "report/tier1_cpu_report/best_cell_updates_per_sec"
    # shapes with nothing to track
    assert ph.extract_entries([1, 2, 3], "m.json") == []
    assert ph.extract_entries({"weird": True}, "w.json") == []
    # non-numeric values never become entries
    assert ph.extract_entries(_bench("fast"), "x.json") == []
    assert ph.extract_entries(_bench(True), "x.json") == []


def test_fold_is_append_only_and_idempotent(tmp_path):
    repo = str(tmp_path)
    _write(os.path.join(repo, "BENCH_r01.json"),
           {"parsed": _bench(1e12, at="2026-01-01")})
    _write(os.path.join(repo, "results", "r2.json"),
           _bench(2e12, at="2026-01-02"))
    hist = os.path.join(repo, "results", "history.jsonl")
    first = ph.fold(repo, hist)
    assert len(first["appended"]) == 2
    assert all("appended_at" in e for e in first["appended"])
    # second fold: identical artifacts, nothing new
    second = ph.fold(repo, hist)
    assert second["appended"] == []
    assert len(second["history"]) == 2
    # the file is line-per-entry JSONL and survives a torn tail line
    with open(hist, "a") as f:
        f.write('{"torn": ')
    assert len(ph.load_history(hist)) == 2
    # a new measurement appends without rewriting old lines
    before = open(hist).read()
    _write(os.path.join(repo, "results", "r3.json"),
           _bench(3e12, at="2026-01-03"))
    third = ph.fold(repo, hist)
    assert len(third["appended"]) == 1
    assert open(hist).read().startswith(before)


def test_unreadable_artifact_is_skipped_not_fatal(tmp_path, capsys):
    repo = str(tmp_path)
    _write(os.path.join(repo, "results", "good.json"), _bench(1e12))
    with open(os.path.join(repo, "results", "bad.json"), "w") as f:
        f.write("{not json")
    entries = ph.scan_repo(repo)
    assert len(entries) == 1


# -- median/MAD anomaly detection ---------------------------------------------


def _entries(series, values):
    return [ph._entry(series, v, "u", f"2026-01-{i + 1:02d}", None, None,
                      f"f{i}.json")
            for i, v in enumerate(values)]


def test_anomaly_robust_z():
    stats = ph.series_stats(_entries("s", [100, 101, 99, 100, 150]))["s"]
    assert stats["median"] == 100 and stats["mad"] == 1
    assert len(stats["anomalies"]) == 1
    a = stats["anomalies"][0]
    assert a["value"] == 150 and a["robust_z"] > ph.ANOMALY_Z


def test_anomaly_mad_zero_fallback():
    """A series of identical values plus one outlier collapses the MAD
    to zero; the 30%-of-median fallback still flags the outlier."""
    stats = ph.series_stats(_entries("s", [100, 100, 100, 100, 150]))["s"]
    assert stats["mad"] == 0
    assert len(stats["anomalies"]) == 1
    assert stats["anomalies"][0]["rel_dev"] == 0.5


def test_no_anomaly_below_min_series():
    stats = ph.series_stats(_entries("s", [100, 100, 900]))["s"]
    assert stats["anomalies"] == []  # 3 < MIN_SERIES: no notion of typical


def test_trend_table_renders_every_series():
    stats = ph.series_stats(_entries("a", [1, 2]) + _entries("b", [3]))
    lines = ph.trend_table(stats)
    assert lines[0].startswith("| series |")
    assert any("| a |" in ln for ln in lines)
    assert any("| b |" in ln for ln in lines)


# -- the CLI contract ---------------------------------------------------------


def test_cli_check_is_read_only_and_strict_gates(tmp_path):
    repo = str(tmp_path)
    for i, v in enumerate([100.0, 100.0, 100.0, 100.0, 150.0]):
        _write(os.path.join(repo, "results", f"r{i}.json"),
               _bench(v, at=f"2026-01-{i + 1:02d}"))
    hist = os.path.join(repo, "results", "history.jsonl")
    # --check: anomalies report, nothing written, informational exit 0
    r = _run(["--repo", repo, "--check"])
    assert r.returncode == 0, r.stderr
    assert "ANOMALY" in r.stdout and not os.path.exists(hist)
    # --check --strict: the same anomaly now gates
    r = _run(["--repo", repo, "--check", "--strict"])
    assert r.returncode == 1
    # a real fold writes the history and the markdown table
    md = os.path.join(repo, "TREND.md")
    r = _run(["--repo", repo, "--markdown", md])
    assert r.returncode == 0
    assert os.path.exists(hist)
    assert open(md).read().startswith("| series |")
    # --json emits machine-readable stats
    r = _run(["--repo", repo, "--json"])
    out = json.loads(r.stdout)
    assert out["perf_history"] is True and out["anomalies"] == 1
    assert out["appended"] == 0  # second fold: idempotent


def test_cli_unusable_input_exits_two(tmp_path):
    r = _run(["--repo", str(tmp_path / "nonexistent")])
    assert r.returncode == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    r = _run(["--repo", str(empty), "--check"])
    assert r.returncode == 2
    assert "nothing to fold" in r.stderr


def test_cli_folds_this_repos_committed_artifacts():
    """The repo's own BENCH_*.json / results/ artifacts parse: the CI
    invocation (--check against the checkout) always has input."""
    r = _run(["--repo", _REPO, "--check"])
    assert r.returncode == 0, r.stderr
    assert "perf_history:" in r.stdout
