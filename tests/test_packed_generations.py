"""Bit-plane Generations path must be bit-identical to the dense stepper."""

import jax.numpy as jnp
import numpy as np
import pytest

from gameoflifewithactors_tpu.models.generations import GenRule, parse_any
from gameoflifewithactors_tpu.ops.generations import multi_step_generations
from gameoflifewithactors_tpu.ops.packed_generations import (
    alive_plane,
    multi_step_packed_generations,
    n_planes,
    pack_generations_for,
    population_packed_generations,
    unpack_generations,
)
from gameoflifewithactors_tpu.ops.stencil import Topology


def _soup(rule, shape=(64, 96), seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, rule.states, size=shape, dtype=np.uint8)


RULES = [
    "brain",          # C=3: 2 planes, C < 2^b (eq-C net)
    "B2/S23/C4",      # C=4: 2 planes, C == 2^b (carry wrap)
    "starwars",       # named C=4 rule from the registry
    "B356/S23/C7",    # C=7: 3 planes
]


def test_n_planes():
    assert n_planes(3) == 2
    assert n_planes(4) == 2
    assert n_planes(5) == 3
    assert n_planes(256) == 8


def test_pack_unpack_roundtrip():
    rule = parse_any("B356/S23/C7")
    g = _soup(rule)
    planes = pack_generations_for(jnp.asarray(g), rule)
    assert planes.shape == (3, 64, 3)
    np.testing.assert_array_equal(np.asarray(unpack_generations(planes)), g)


@pytest.mark.parametrize("rule_s", RULES)
@pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
def test_bit_identity_vs_dense(rule_s, topology):
    rule = parse_any(rule_s)
    assert isinstance(rule, GenRule)
    g = _soup(rule, seed=hash(rule_s) % 1000)
    want = np.asarray(multi_step_generations(
        jnp.asarray(g), 24, rule=rule, topology=topology))
    planes = pack_generations_for(jnp.asarray(g), rule)
    got_planes = multi_step_packed_generations(
        planes, 24, rule=rule, topology=topology)
    np.testing.assert_array_equal(np.asarray(unpack_generations(got_planes)), want)


def test_alive_plane_and_population():
    rule = parse_any("brain")
    g = _soup(rule, seed=3)
    planes = pack_generations_for(jnp.asarray(g), rule)
    alive = np.asarray(unpack_generations(jnp.stack(
        [alive_plane(planes)] + [jnp.zeros_like(planes[0])])))
    np.testing.assert_array_equal(alive, (g == 1).astype(np.uint8))
    assert population_packed_generations(planes) == int((g == 1).sum())


def test_donation_contract():
    rule = parse_any("brain")
    planes = pack_generations_for(jnp.asarray(_soup(rule, seed=9)), rule)
    a = multi_step_packed_generations(planes, 5, rule=rule)
    assert not planes.is_deleted()
    b = multi_step_packed_generations(planes, 5, rule=rule)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = multi_step_packed_generations(planes, 5, rule=rule, donate=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


def test_engine_routes_generations_to_bit_planes():
    from gameoflifewithactors_tpu import Engine

    g = _soup(parse_any("brain"), shape=(48, 64), seed=12)
    fast = Engine(g, "brain")                      # auto -> packed -> planes
    slow = Engine(g, "brain", backend="dense")
    assert fast._gen_packed and not slow._gen_packed
    assert fast.state.shape == (2, 48, 2)
    fast.step(17)
    slow.step(17)
    np.testing.assert_array_equal(fast.snapshot(), slow.snapshot())
    assert fast.population() == slow.population()
    # checkpoint round-trip exercises the v3 genplanes32 device layout
    import tempfile, os
    from gameoflifewithactors_tpu.utils import checkpoint as ckpt
    with tempfile.TemporaryDirectory() as d:
        path = ckpt.save(fast, os.path.join(d, "c.npz"))
        back = ckpt.load_engine(path)
        np.testing.assert_array_equal(back.snapshot(), fast.snapshot())
        assert back.generation == 17


def test_sharded_bit_planes_match_single_device():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib, sharded

    rule = parse_any("brain")
    g = _soup(rule, shape=(64, 256), seed=31)
    want = np.asarray(multi_step_generations(
        jnp.asarray(g), 14, rule=rule, topology=Topology.TORUS))
    m = mesh_lib.make_mesh((2, 4))
    planes = pack_generations_for(jnp.asarray(g), rule)
    planes = jax.device_put(
        planes, NamedSharding(m, P(None, mesh_lib.ROW_AXIS, mesh_lib.COL_AXIS)))
    run = sharded.make_multi_step_generations_packed(m, rule, Topology.TORUS)
    got = np.asarray(unpack_generations(run(planes, 14)))
    np.testing.assert_array_equal(got, want)
