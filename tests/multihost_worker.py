"""Worker for tests/test_multihost.py: one process of the distributed rig.

Invoked as: python multihost_worker.py <process_id> <num_processes> <port>.
Each process owns 2 virtual CPU devices; together they form a (2, N) global
mesh stepping a torus-sharded grid whose glider crosses process boundaries.
Prints MULTIHOST-OK on bit-identity with the single-device engine.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
import axon_guard  # noqa: E402

axon_guard.strip_import_path()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> None:
    pid, n_procs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])

    from gameoflifewithactors_tpu.models import seeds
    from gameoflifewithactors_tpu.models.rules import CONWAY
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.ops.packed import multi_step_packed
    from gameoflifewithactors_tpu.ops.stencil import Topology
    from gameoflifewithactors_tpu.parallel import multihost, sharded

    multihost.initialize(f"localhost:{port}", n_procs, pid)
    assert jax.process_count() == n_procs
    assert len(jax.devices()) == 2 * n_procs

    mesh = multihost.global_mesh((2, n_procs))
    gens = 120
    grid = seeds.seeded((64, 64 * n_procs), "glider", 1, 1)
    packed = bitpack.pack_np(grid)

    p = multihost.put_global_grid(packed, mesh)
    run = sharded.make_multi_step_packed(mesh, CONWAY, Topology.TORUS)
    out = run(p, gens)
    got = multihost.gather_global(out)

    want = np.asarray(multi_step_packed(
        jnp.asarray(packed), gens, rule=CONWAY, topology=Topology.TORUS))
    np.testing.assert_array_equal(got, want)
    assert got.sum() > 0  # the glider is alive somewhere

    # communication-avoiding runner across REAL process boundaries: one
    # depth-g exchange per g generations, still bit-identical
    g = 8
    assert gens % g == 0
    deep = sharded.make_multi_step_packed_deep(
        mesh, CONWAY, Topology.TORUS, gens_per_exchange=g)
    got_deep = multihost.gather_global(deep(p, gens // g))  # p still live
    np.testing.assert_array_equal(got_deep, want)

    # row-band runner driving the Pallas slab kernel (interpret mode on
    # this CPU rig; the kernel is native-proven on-chip — results/
    # tpu_worklist.json pallas_band): every process owns 2 full-width
    # bands, the depth-g halo ppermutes cross REAL process boundaries
    bmesh = multihost.global_mesh((2 * n_procs, 1))
    bgrid = seeds.seeded((8 * 2 * n_procs, 64), "glider", 1, 1)
    bpacked = bitpack.pack_np(bgrid)
    bp = multihost.put_global_grid(bpacked, bmesh)
    brun = sharded.make_multi_step_pallas(bmesh, CONWAY, gens_per_exchange=8)
    got_band = multihost.gather_global(brun(bp, 5))
    want_band = np.asarray(multi_step_packed(
        jnp.asarray(bpacked), 40, rule=CONWAY, topology=Topology.TORUS))
    np.testing.assert_array_equal(got_band, want_band)

    # per-tile sharded sparse across REAL process boundaries: the gun's
    # activity map makes its own halo trip between processes, and tiles on
    # the far processes stay asleep while staying bit-exact
    from gameoflifewithactors_tpu.ops import sparse as sparse_ops

    sgrid = seeds.seeded((64, 64 * n_procs), "gosper_gun", 10, 12)
    spacked = bitpack.pack_np(sgrid)
    tr, tw = 16, 1
    srun = sharded.make_multi_step_packed_sparse_tiled(
        mesh, CONWAY, Topology.TORUS, tile_rows=tr, tile_words=tw)
    act_np = np.asarray(sparse_ops.tile_activity(
        jnp.asarray(spacked), tr, tw).astype(jnp.uint32))
    sout, sact = srun(multihost.put_global_grid(spacked, mesh),
                      multihost.put_global_grid(act_np, mesh), 40)
    want_sparse = np.asarray(multi_step_packed(
        jnp.asarray(spacked), 40, rule=CONWAY, topology=Topology.TORUS))
    np.testing.assert_array_equal(multihost.gather_global(sout), want_sparse)
    n_awake = int(multihost.gather_global(sact).sum())
    assert 0 < n_awake < act_np.size, n_awake  # gun corner only

    # flattened-band kernel path on the genuine 2D (2, n) mesh: the band
    # ppermutes ride the flattened ('x', 'y') axis ACROSS processes
    # (round-4 feature — config #3's mesh shape with the native path)
    fb_grid = seeds.seeded((8 * 2 * n_procs, 64), "glider", 1, 1)
    fb_packed = bitpack.pack_np(fb_grid)
    fb_run = sharded.make_multi_step_pallas(mesh, CONWAY, gens_per_exchange=8)
    fb_out = multihost.gather_global(fb_run(
        multihost.put_global_grid(fb_packed, mesh, banded=True), 3))
    fb_want = np.asarray(multi_step_packed(
        jnp.asarray(fb_packed), 24, rule=CONWAY, topology=Topology.TORUS))
    np.testing.assert_array_equal(fb_out, fb_want)

    # multi-state (C >= 3) LtL plane stack across processes: ONE stacked
    # ppermute of r halo rows + 1 halo word per side crosses the boundary
    from gameoflifewithactors_tpu.models.generations import parse_any
    from gameoflifewithactors_tpu.ops.packed_generations import (
        pack_generations_for,
        unpack_generations,
    )
    from gameoflifewithactors_tpu.ops.packed_ltl import multi_step_ltl_planes

    mrule = parse_any("R2,C4,M1,S3..8,B5..9")
    rng = np.random.default_rng(9)  # same seed => same grid on every proc
    mgrid = rng.integers(0, 4, size=(32, 64 * n_procs), dtype=np.uint8)
    mplanes = np.asarray(pack_generations_for(jnp.asarray(mgrid), mrule))
    mrun = sharded.make_multi_step_ltl_planes(mesh, mrule, Topology.TORUS)
    mout = multihost.gather_global(mrun(
        multihost.put_global_grid(mplanes, mesh), 6))
    mwant = np.asarray(multi_step_ltl_planes(
        jnp.asarray(mplanes), 6, rule=mrule, topology=Topology.TORUS))
    np.testing.assert_array_equal(mout, mwant)
    assert (np.asarray(unpack_generations(jnp.asarray(mout))) < 4).all()

    # sharded elementary (rows DP x width CP) across processes: the halo
    # word crosses the process boundary every chunk
    from gameoflifewithactors_tpu.models.elementary import parse_elementary
    from gameoflifewithactors_tpu.ops.elementary import multi_step_elementary

    w110 = parse_elementary("W110")
    erow = np.zeros((4, 64 * n_procs), np.uint8)
    erow[:, ::7] = 1  # deterministic, same on every process
    epacked = bitpack.pack_np(erow)
    erun = sharded.make_multi_step_elementary_sharded(
        mesh, w110, Topology.TORUS, gens_per_exchange=8)
    eout = multihost.gather_global(
        erun(multihost.put_global_grid(epacked, mesh), 3))
    want_e = np.asarray(multi_step_elementary(
        jnp.asarray(epacked), 24, rule=w110, topology=Topology.TORUS))
    np.testing.assert_array_equal(eout, want_e)

    # distributed checkpoint/resume: gather the LIVE sharded state to this
    # host mid-run, serialize it, restore onto a fresh global placement,
    # and resume — the recovery path a lost-process restart takes
    # (SURVEY §6 failure-detection row composed with the multi-host
    # runtime). Every process does the full round trip independently and
    # must land on the 120-generation oracle bit-exactly.
    import tempfile

    half = multihost.gather_global(
        run(multihost.put_global_grid(packed, mesh), 60))
    fd, ckpath = tempfile.mkstemp(suffix=f"_mh{pid}.npz")
    os.close(fd)
    try:
        np.savez(ckpath, grid=half, generation=60)
        loaded = np.load(ckpath)
        assert int(loaded["generation"]) == 60
        resumed = multihost.gather_global(
            run(multihost.put_global_grid(loaded["grid"], mesh), 60))
    finally:
        os.unlink(ckpath)
    np.testing.assert_array_equal(resumed, want)

    print(f"MULTIHOST-OK proc={pid}/{n_procs} devices={len(jax.devices())}",
          flush=True)


if __name__ == "__main__":
    main()
