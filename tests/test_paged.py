"""Paged tile-pool grid memory (memory/, ROADMAP item 3).

The claims under test:

- **bit-identity** — a universe split across pool pages, with halos
  resolved by page-table gather and missing pages aliasing the dead
  tile, equals the dense NumPy oracle exactly: Conway, Larger-than-Life
  and Generations, both topologies;
- **unbounded flight** — a glider on a ``bounds=None`` plane crosses
  page boundaries indefinitely while the pool footprint stays constant
  (pages allocate at the wake front, retire behind);
- **pool pressure** — exhaustion raises :class:`PoolExhausted` at the
  allocator and stalls only the starved grid in the multi-tenant pump;
  releases reclaim, and the gauges/counters track every transition;
- **zero retraces** — after :meth:`TilePool.warm`, allocation churn,
  page retirement and stepping never compile (``retrace_budget(0)``);
- **sparse payoff** — a 4096² universe that is ~2% live binds < 10% of
  the dense tile count;
- **checkpoint** — ``save_paged``/``load_paged`` round-trips the sparse
  page list bit-exactly, and the restored grid keeps flying identically.
"""

import numpy as np
import pytest

from gameoflifewithactors_tpu.analysis.sanitizers import retrace_budget
from gameoflifewithactors_tpu.engine import Engine
from gameoflifewithactors_tpu.memory import (
    DEAD_SLOT,
    PagedEngineState,
    PagedGrid,
    PagedUniverse,
    PoolExhausted,
    TilePool,
    step_grids,
)
from gameoflifewithactors_tpu.models.generations import GenRule, parse_any
from gameoflifewithactors_tpu.models.ltl import BOSCO, LtLRule
from gameoflifewithactors_tpu.obs.registry import MetricsRegistry
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.stencil import Topology
from gameoflifewithactors_tpu.serve import lanes as serve_lanes

from .oracle import numpy_run
from .test_generations import oracle as generations_oracle
from .test_ltl import oracle as ltl_oracle

GLIDER = ((0, 1), (1, 2), (2, 0), (2, 1), (2, 2))  # flies down-right


def glider_cells(h=8, w=8, at=(0, 0)):
    cells = np.zeros((h, w), np.uint8)
    for y, x in GLIDER:
        cells[at[0] + y, at[1] + x] = 1
    return cells


def soup(rule, h, w, fill=0.35, seed=0):
    rng = np.random.default_rng(seed)
    states = getattr(parse_any(rule), "states", 2)
    if states > 2:
        return rng.integers(0, states, size=(h, w), dtype=np.uint8)
    return (rng.random((h, w)) < fill).astype(np.uint8)


def reference(grid, rule, topology, n):
    """Per-family dense NumPy oracle (each family's own test module)."""
    rule = parse_any(rule)
    torus = topology is Topology.TORUS
    if isinstance(rule, LtLRule):
        return ltl_oracle(grid, rule, torus, n)
    if isinstance(rule, GenRule):
        return generations_oracle(grid, rule, torus, n)
    return numpy_run(grid, rule, topology, n)


def pack2d(cells):
    """(H, W) binary cells -> (1, H, W/32) words for PagedGrid.seed_words."""
    return np.asarray(bitpack.pack_np(np.asarray(cells, np.uint8)))[None]


# -- oracle bit-identity through the Engine's paged backend -------------------


@pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
@pytest.mark.parametrize("rule,shape,opts", [
    ("B3/S23", (64, 64), {"tile_rows": 16, "tile_words": 1}),
    ("B3/S23", (64, 128), {"tile_rows": 32, "tile_words": 2}),
    (BOSCO.notation, (64, 64), {"tile_rows": 16, "tile_words": 2}),
    ("B2/S/C3", (64, 64), {"tile_rows": 16, "tile_words": 1}),
])
def test_paged_engine_matches_oracle(rule, shape, opts, topology):
    grid = soup(rule, *shape, seed=7)
    eng = Engine(grid, rule, topology=topology, backend="paged",
                 sparse_opts=opts)
    eng.step(13)
    want = reference(grid, rule, topology, 13)
    assert np.array_equal(eng.snapshot(), want)
    assert eng.backend == "paged"
    if want.any():
        assert eng.active_tiles() > 0
    else:
        # an extinct universe retires every page (BOSCO soups at this
        # density die out) — extinction costs zero tiles
        assert eng.active_tiles() == 0


def test_paged_backend_rejects_mesh_and_b0():
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

    with pytest.raises(ValueError, match="single-device"):
        Engine(np.zeros((64, 64), np.uint8), "B3/S23",
               mesh=mesh_lib.make_mesh(), backend="paged")
    # birth-from-nothing breaks "missing page = dead tile" closure
    with pytest.raises(ValueError, match="birth"):
        Engine(np.zeros((64, 64), np.uint8), "B0/S8", backend="paged")


def test_paged_engine_set_grid_reseeds_through_pool():
    grid = soup("B3/S23", 64, 64, seed=11)
    eng = Engine(grid, "B3/S23", backend="paged",
                 sparse_opts={"tile_rows": 16, "tile_words": 1})
    eng.step(9)
    eng.set_grid(grid, 0)
    eng.step(9)
    assert np.array_equal(eng.snapshot(),
                          reference(grid, "B3/S23", Topology.TORUS, 9))


# -- unbounded flight ---------------------------------------------------------


def test_glider_crosses_page_boundaries_with_constant_footprint():
    """A glider on the unbounded plane crosses >= 3 page boundaries
    (tile rows are 16 cells; 256 generations move it 64 cells) while the
    pool footprint stays a constant handful of tiles and the trail
    retires back to the free list."""
    reg = MetricsRegistry()
    pool = TilePool("B3/S23", 64, tile_rows=16, tile_words=1,
                    name="flight", registry=reg)
    u = PagedUniverse(pool.rule, pool=pool)
    u.seed_cells(glider_cells(), origin=(1, 1))
    u.pool.warm()
    row_bands = set()
    for _ in range(16):
        u.step(16)
        assert u.population() == 5
        (ty0, _tx0), _ = u.grid.live_tile_bbox()
        row_bands.add(ty0)
        # constant footprint: live page + one wake ring, never the trail
        assert pool.in_use() <= 12
    assert len(row_bands) >= 4  # >= 3 tile-row boundary crossings
    (ty0, _tx0), _ = u.grid.live_tile_bbox()
    assert ty0 >= 4, "glider never left its seed pages"
    # retirement actually reclaimed the trail
    assert reg.counter("pool_reclaim_total").value(pool="flight") > 0


def test_unbounded_matches_bounded_oracle_mid_flight():
    """The unbounded plane's glider, windowed out, equals the dense DEAD
    oracle of a grid big enough to contain the flight."""
    side = 96
    cells = np.zeros((side, side), np.uint8)
    cells[1:9, 1:9] = glider_cells()
    u = PagedUniverse("B3/S23", capacity=128, tile_rows=16, tile_words=1)
    u.seed_cells(cells[:16, :32], origin=(0, 0))
    u.step(200)
    want = reference(cells, "B3/S23", Topology.DEAD, 200)
    origin, got = u.snapshot_cells()
    dense = np.zeros((side, side), np.uint8)
    dense[origin[0]:origin[0] + got.shape[0],
          origin[1]:origin[1] + got.shape[1]] = got
    assert np.array_equal(dense, want)


# -- pool pressure, eviction and reclaim --------------------------------------


def test_pool_exhaustion_raises_and_counts():
    reg = MetricsRegistry()
    pool = TilePool("B3/S23", 4, tile_rows=16, tile_words=1,
                    name="tiny", registry=reg)
    slots = [pool.alloc() for _ in range(3)]
    assert DEAD_SLOT not in slots
    assert pool.free_count() == 0 and pool.in_use() == 3
    with pytest.raises(PoolExhausted):
        pool.alloc()
    assert reg.counter("pool_oom_total").value(pool="tiny") == 1
    assert reg.gauge("pool_tiles_free").value(pool="tiny") == 0
    pool.release(slots[0])
    assert pool.free_count() == 1
    assert pool.alloc() == slots[0]  # reclaimed slot comes back
    with pytest.raises(ValueError):
        pool.release(DEAD_SLOT)


def test_pool_pressure_stalls_only_the_starved_grid():
    """Two grids on one small pool: when one cannot provision its wake
    ring, step_grids stalls IT for the rest of the call and keeps
    stepping the co-tenant; releasing pressure un-stalls it."""
    pool = TilePool("B3/S23", 12, tile_rows=16, tile_words=1)
    a = PagedGrid(pool, topology=Topology.TORUS, bounds=(2, 1))
    b = PagedGrid(pool, topology=Topology.DEAD, bounds=None)
    a.seed_words(pack2d(soup("B3/S23", 32, 32, seed=3)))
    b.seed_words(pack2d(glider_cells(16, 32, at=(6, 14))))
    # burn the free list so b's wake ring cannot bind
    hoard = [pool.alloc() for _ in range(pool.free_count())]
    done = step_grids(pool, [a, b], 8)
    assert done[0] == 8, "torus grid (no new pages needed) must not stall"
    assert done[1] < 8, "unbounded grid must stall on the empty pool"
    for s in hoard:
        pool.release(s)
    done = step_grids(pool, [b], 8)  # pressure released: b catches up
    assert done[0] == 8


def test_release_restores_free_slots_are_zero_invariant():
    pool = TilePool("B3/S23", 4, tile_rows=16, tile_words=1)
    slot = pool.alloc()
    pool.write(slot, np.full((1, 16, 1), 0xFFFFFFFF, np.uint32))
    pool.release(slot)
    assert not pool.tiles_host()[slot].any()
    assert (pool.neighbors[slot] == DEAD_SLOT).all()


# -- zero retraces across allocation churn ------------------------------------


def test_retrace_budget_zero_across_allocation_churn():
    """After warm, a page-crossing glider (allocating at the front,
    retiring behind, every chunk) never compiles — and neither does a
    full drop + reseed (release/alloc/write churn)."""
    pool = TilePool("B3/S23", 64, tile_rows=16, tile_words=1)
    u = PagedUniverse(pool.rule, pool=pool)
    u.seed_cells(glider_cells(), origin=(1, 1))
    pool.warm()
    with retrace_budget(0, context="paged allocation churn"):
        for _ in range(24):
            u.step(16)
        u.grid.drop()
        u.seed_cells(glider_cells(), origin=(5, 5))
        u.step(64)
    assert u.population() == 5


# -- the sparse payoff --------------------------------------------------------


def test_4096_mostly_empty_universe_allocates_under_10pct_of_dense():
    """ISSUE 20 acceptance: a 4096 x 4096 logical universe <= 2% live
    (one clustered soup) binds < 10% of the dense tile count, and steps
    bit-identically to the packed dense engine."""
    side = 4096
    grid = np.zeros((side, side), np.uint8)
    grid[1792:2304, 1792:2304] = soup("B3/S23", 512, 512, seed=5)
    live_frac = grid.sum() / grid.size
    assert live_frac <= 0.02
    eng = Engine(grid, "B3/S23", topology=Topology.DEAD, backend="paged",
                 sparse_opts={"tile_rows": 32, "tile_words": 4})
    dense_tiles = (side // 32) * ((side // 32) // 4)
    assert dense_tiles == 4096
    eng.step(3)
    assert eng.active_tiles() < dense_tiles // 10, \
        f"{eng.active_tiles()} tiles bound for a {live_frac:.1%}-live grid"
    ref = Engine(grid, "B3/S23", topology=Topology.DEAD, backend="packed")
    ref.step(3)
    assert np.array_equal(eng.snapshot(), ref.snapshot())


# -- checkpoint/resume --------------------------------------------------------


def test_save_load_paged_round_trip_bit_identical(tmp_path):
    from gameoflifewithactors_tpu.utils import checkpoint as ckpt

    u = PagedUniverse("B3/S23", capacity=64, tile_rows=16, tile_words=1)
    u.seed_cells(glider_cells(), origin=(1, 1))
    u.step(100)
    path = ckpt.save_paged(u, tmp_path / "glider.npz")
    grid2, meta = ckpt.load_paged(path)
    assert meta["generation"] == 100
    twin = PagedUniverse(grid2.pool.rule, pool=grid2.pool)
    twin.grid = grid2
    u.step(100)
    twin.step(100)
    assert u.generation == twin.generation == 200
    o1, c1 = u.snapshot_cells()
    o2, c2 = twin.snapshot_cells()
    assert o1 == o2 and np.array_equal(c1, c2)


def test_load_paged_refuses_garbage_and_mismatched_pool(tmp_path):
    from gameoflifewithactors_tpu.utils import checkpoint as ckpt

    u = PagedUniverse("B3/S23", capacity=16, tile_rows=16, tile_words=1)
    u.seed_cells(glider_cells())
    path = ckpt.save_paged(u, tmp_path / "u.npz")
    with pytest.raises(ValueError, match="does not match"):
        ckpt.load_paged(path, pool=TilePool("B3/S23", 16, tile_rows=32,
                                            tile_words=1))
    bad = tmp_path / "bad.npz"
    bad.write_bytes(b"not an npz")
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.load_paged(bad)


def test_paged_engine_checkpoints_through_engine_save(tmp_path):
    """The bounded paged engine rides the ordinary packed32 checkpoint
    (save reads .state, which reconstructs dense words)."""
    from gameoflifewithactors_tpu.utils import checkpoint as ckpt

    grid = soup("B3/S23", 64, 64, seed=2)
    eng = Engine(grid, "B3/S23", backend="paged",
                 sparse_opts={"tile_rows": 16, "tile_words": 1})
    eng.step(7)
    path = ckpt.save(eng, tmp_path / "e.npz")
    eng2 = ckpt.load_engine(path, backend="paged")
    eng.step(7)
    eng2.step(7)
    assert np.array_equal(eng.snapshot(), eng2.snapshot())
    assert eng2.generation == 14


# -- runner-cache geometry keys (regression) ----------------------------------


def test_lane_runner_cache_keys_include_pool_geometry():
    """Regression: a resized pool slab must NOT alias the executable
    compiled for the old geometry — the module-level runner cache keys
    carry (rule, tile_rows, tile_words)."""
    rule = parse_any("B3/S23")
    r16 = serve_lanes.paged_lane_runner(rule, 16, 1)
    r32 = serve_lanes.paged_lane_runner(rule, 32, 1)
    r16w = serve_lanes.paged_lane_runner(rule, 16, 2)
    assert r16 is not r32 and r16 is not r16w and r32 is not r16w
    assert serve_lanes.paged_lane_runner(rule, 16, 1) is r16  # cache hit
    assert serve_lanes.paged_lane_runner(BOSCO, 16, 1) is not r16
    # and both geometries actually run through their keyed runners
    for tr, runner in ((16, r16), (32, r32)):
        pool = TilePool(rule, 4, tile_rows=tr, tile_words=1, runner=runner)
        g = PagedGrid(pool, topology=Topology.TORUS, bounds=(1, 1))
        g.seed_words(pack2d(soup("B3/S23", tr, 32, seed=1)))
        assert step_grids(pool, [g], 4)[0] == 4


def test_pool_capacity_for_ladder_maps_old_configs():
    """MIGRATING contract: the ladder-collapse mapping sizes the pool
    from the old ladder's top rung."""
    cap = serve_lanes.pool_capacity_for_ladder((1, 8, 64, 256))
    assert cap == 1 + 8 * serve_lanes.TILES_PER_SLOT * 256
    assert serve_lanes.pool_capacity_for_ladder((1,)) > 1
