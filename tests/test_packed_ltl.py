"""Bit-packed LtL must be bit-identical to the dense log-tree stepper."""

import jax.numpy as jnp
import numpy as np
import pytest

from gameoflifewithactors_tpu.models.ltl import parse_ltl
from gameoflifewithactors_tpu.ops import bitpack
from gameoflifewithactors_tpu.ops.ltl import multi_step_ltl
from gameoflifewithactors_tpu.ops.packed_ltl import (
    bs_add,
    bs_ge,
    box_counts_packed,
    hshift_east,
    hshift_west,
    multi_step_ltl_packed,
    vshift,
)
from gameoflifewithactors_tpu.ops.stencil import Topology


def _soup(shape=(64, 96), seed=0, p=0.5):
    rng = np.random.default_rng(seed)
    return (rng.random(shape) < p).astype(np.uint8)


def _bs_to_int(planes):
    out = None
    for i, p in enumerate(planes):
        part = np.asarray(bitpack.unpack(p)).astype(np.int64) << i
        out = part if out is None else out + part
    return out


@pytest.mark.parametrize("d", [1, 3, 31, 32, 33, 40])
@pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
def test_cell_shifts_match_numpy(d, topology):
    g = _soup((16, 96), seed=d)
    p = bitpack.pack(jnp.asarray(g))
    west = np.asarray(bitpack.unpack(hshift_west(p, d, topology)))
    east = np.asarray(bitpack.unpack(hshift_east(p, d, topology)))
    down = np.asarray(bitpack.unpack(vshift(p, d % 16 or 1, topology)))
    if topology is Topology.TORUS:
        np.testing.assert_array_equal(west, np.roll(g, d, axis=1))
        np.testing.assert_array_equal(east, np.roll(g, -d, axis=1))
        np.testing.assert_array_equal(down, np.roll(g, d % 16 or 1, axis=0))
    else:
        w = np.zeros_like(g); w[:, d:] = g[:, :-d]
        e = np.zeros_like(g); e[:, :-d] = g[:, d:]
        np.testing.assert_array_equal(west, w)
        np.testing.assert_array_equal(east, e)


def test_bs_add_and_ge():
    rng = np.random.default_rng(1)
    a = rng.integers(0, 100, (8, 64), dtype=np.uint8)
    b = rng.integers(0, 100, (8, 64), dtype=np.uint8)
    ap = [bitpack.pack(jnp.asarray((a >> i) & 1)) for i in range(7)]
    bp = [bitpack.pack(jnp.asarray((b >> i) & 1)) for i in range(7)]
    s = bs_add(ap, bp)
    np.testing.assert_array_equal(_bs_to_int(s), a.astype(np.int64) + b)
    for c in (0, 1, 57, 99, 200):
        got = np.asarray(bitpack.unpack(bs_ge(ap, c))).astype(bool)
        np.testing.assert_array_equal(got, a >= c)


@pytest.mark.parametrize("radius", [1, 2, 5, 7])
@pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
def test_box_counts_match_numpy(radius, topology):
    g = _soup((48, 64), seed=radius)
    p = bitpack.pack(jnp.asarray(g))
    got = _bs_to_int(box_counts_packed(p, radius, topology))
    pad = (np.pad(g, radius, mode="wrap") if topology is Topology.TORUS
           else np.pad(g, radius))
    k = 2 * radius + 1
    want = sum(
        pad[dy:dy + 48, dx:dx + 64].astype(np.int64)
        for dy in range(k) for dx in range(k)
    )
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("rule_s", ["bosco", "majority", "R3,C0,M0,S10..25,B12..20"])
@pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
def test_bit_identity_vs_dense(rule_s, topology):
    rule = parse_ltl(rule_s)
    g = _soup((64, 96), seed=hash(rule_s) % 997)
    want = np.asarray(multi_step_ltl(
        jnp.asarray(g), 12, rule=rule, topology=topology))
    p = bitpack.pack(jnp.asarray(g))
    got_p = multi_step_ltl_packed(p, 12, rule=rule, topology=topology)
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack(got_p)), want)


def test_donation_contract():
    rule = parse_ltl("bosco")
    p = bitpack.pack(jnp.asarray(_soup(seed=5)))
    a = multi_step_ltl_packed(p, 4, rule=rule)
    assert not p.is_deleted()
    b = multi_step_ltl_packed(p, 4, rule=rule, donate=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_engine_routes_ltl_to_packed():
    from gameoflifewithactors_tpu import Engine

    g = _soup((64, 96), seed=21, p=0.4)
    # off-TPU, auto resolves LtL to the dense byte path (the bit-sliced
    # path is a TPU-VPU design; it measured slower under CPU XLA)
    assert Engine(g, "bosco").backend == "dense"
    fast = Engine(g, "bosco", backend="packed")    # explicit: bit-sliced
    slow = Engine(g, "bosco", backend="dense")
    assert fast._ltl_packed and fast._packed and not slow._ltl_packed
    fast.step(9)
    slow.step(9)
    np.testing.assert_array_equal(fast.snapshot(), slow.snapshot())
    assert fast.population() == slow.population()
    # width not divisible by 32 falls back to the dense layout
    odd = Engine(_soup((64, 100), seed=2), "bosco", backend="packed")
    assert not odd._ltl_packed
    odd.step(2)


class TestShardedPackedLtL:
    @pytest.mark.parametrize("rule_s", ["bosco", "majority"])
    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    def test_bit_identity_vs_single_device(self, rule_s, topology):
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib, sharded

        rule = parse_ltl(rule_s)
        g = _soup((64, 256), seed=len(rule_s), p=0.4)
        want = np.asarray(multi_step_ltl(
            jnp.asarray(g), 10, rule=rule, topology=topology))
        m = mesh_lib.make_mesh((2, 4))
        p = mesh_lib.device_put_sharded_grid(bitpack.pack(jnp.asarray(g)), m)
        run = sharded.make_multi_step_ltl_packed(m, rule, topology)
        got = np.asarray(bitpack.unpack(run(p, 10)))
        np.testing.assert_array_equal(got, want)

    def test_tile_smaller_than_radius_raises(self):
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib, sharded

        rule = parse_ltl("R7,C0,M1,S1..40,B1..40")
        m = mesh_lib.make_mesh((8, 1))
        p = mesh_lib.device_put_sharded_grid(
            bitpack.pack(jnp.zeros((32, 32), jnp.uint8)), m)  # 4-row tiles
        run = sharded.make_multi_step_ltl_packed(m, rule, Topology.TORUS)
        with pytest.raises(ValueError, match="smaller than the rule radius"):
            run(p, 1)


class TestMultiStatePlanes:
    """C >= 3 LtL on the bit-plane stack (ops/packed_ltl.step_ltl_planes):
    the Generations decay machine driven by radius-r interval counts —
    bit-identical to the dense byte path (ops/ltl.py multistate branch)."""

    @pytest.mark.parametrize("spec,n", [
        ("R2,C4,M1,S3..8,B5..9", 12),        # box, C=4
        ("R3,C5,M0,S6..14,B8..12", 8),       # M0: center excluded
        ("R2,C3,M0,S6..11,B6..9,NN", 10),    # von Neumann decay
        ("R1,C6,S2-3,B3,NM", 16),            # HROT list form, C=6
    ])
    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    def test_bit_identity_vs_dense(self, spec, n, topology):
        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.packed_generations import (
            pack_generations_for,
            unpack_generations,
        )
        from gameoflifewithactors_tpu.ops.packed_ltl import (
            multi_step_ltl_planes,
        )

        rule = parse_any(spec)
        rng = np.random.default_rng(len(spec))
        grid = rng.integers(0, rule.states, size=(64, 96), dtype=np.uint8)
        want = np.asarray(multi_step_ltl(
            jnp.asarray(grid), n, rule=rule, topology=topology))
        planes = pack_generations_for(jnp.asarray(grid), rule)
        got = np.asarray(unpack_generations(
            multi_step_ltl_planes(planes, n, rule=rule, topology=topology)))
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("topology", [Topology.TORUS, Topology.DEAD])
    def test_sharded_planes_bit_identity(self, topology):
        from gameoflifewithactors_tpu.models.generations import parse_any
        from gameoflifewithactors_tpu.ops.packed_generations import (
            pack_generations_for,
            unpack_generations,
        )
        from gameoflifewithactors_tpu.parallel import mesh as mesh_lib, sharded

        rule = parse_any("R2,C4,M1,S3..8,B5..9")
        rng = np.random.default_rng(31)
        grid = rng.integers(0, 4, size=(64, 256), dtype=np.uint8)
        want = np.asarray(multi_step_ltl(
            jnp.asarray(grid), 9, rule=rule, topology=topology))
        m = mesh_lib.make_mesh((2, 4))
        planes = mesh_lib.device_put_sharded_grid(
            pack_generations_for(jnp.asarray(grid), rule), m)
        run = sharded.make_multi_step_ltl_planes(m, rule, topology)
        got = np.asarray(unpack_generations(run(planes, 9)))
        np.testing.assert_array_equal(got, want)

    def test_engine_facade_routes_planes(self):
        from gameoflifewithactors_tpu import Engine

        rng = np.random.default_rng(41)
        grid = rng.integers(0, 4, size=(64, 96), dtype=np.uint8)
        ref = Engine(grid, "R2,C4,M1,S3..8,B5..9", backend="dense")
        got = Engine(grid, "R2,C4,M1,S3..8,B5..9", backend="packed")
        assert got._ltl_planes and got._gen_packed and not got._ltl_packed
        ref.step(11)
        got.step(11)
        np.testing.assert_array_equal(ref.snapshot(), got.snapshot())
        assert ref.population() == got.population()
        # a width that cannot pack still warns down to the dense path
        import warnings as w

        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            odd = Engine(np.zeros((32, 48), np.uint8),
                         "R2,C4,M1,S3..8,B5..9", backend="packed")
        assert any("dense byte path" in str(c.message) for c in caught)
        assert not odd._ltl_planes and odd.backend == "dense"

    def test_planes_entry_rejects_binary(self):
        from gameoflifewithactors_tpu.ops.packed_ltl import step_ltl_planes

        with pytest.raises(ValueError, match="C >= 3"):
            step_ltl_planes((jnp.zeros((8, 1), jnp.uint32),),
                            parse_ltl("bosco"), Topology.TORUS)
