"""Checkpoint/resume exactness, config assembly, and CLI end-to-end."""

import io
import json

import numpy as np
import pytest

from gameoflifewithactors_tpu import Engine, SimulationConfig
from gameoflifewithactors_tpu.cli import main as cli_main
from gameoflifewithactors_tpu.config import from_args
from gameoflifewithactors_tpu.models import seeds
from gameoflifewithactors_tpu.ops.stencil import Topology
from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
from gameoflifewithactors_tpu.utils import checkpoint as ckpt


def test_checkpoint_roundtrip_exact(tmp_path):
    rng = np.random.default_rng(9)
    g = rng.integers(0, 2, size=(48, 96), dtype=np.uint8)
    e = Engine(g, "highlife", topology=Topology.DEAD)
    e.step(7)
    path = ckpt.save(e, tmp_path / "ck.npz")

    e2 = ckpt.load_engine(path)
    assert e2.generation == 7
    assert e2.rule == e.rule and e2.topology == Topology.DEAD
    np.testing.assert_array_equal(e2.snapshot(), e.snapshot())

    # resumed run continues exactly as the original would
    e.step(5)
    e2.step(5)
    np.testing.assert_array_equal(e2.snapshot(), e.snapshot())


def test_checkpoint_cross_backend_and_mesh(tmp_path):
    g = seeds.seeded((32, 256), "gosper_gun", 4, 4)
    e = Engine(g, "conway", backend="dense")
    e.step(30)
    path = ckpt.save(e, tmp_path / "ck.npz")

    m = mesh_lib.make_mesh((2, 4))
    e2 = ckpt.load_engine(path, mesh=m, backend="packed")
    e.step(30)
    e2.step(30)
    np.testing.assert_array_equal(e2.snapshot(), e.snapshot())


def test_checkpoint_version_guard(tmp_path):
    p = tmp_path / "bad.npz"
    np.savez(p, bits=np.zeros((1, 1), np.uint8), meta=json.dumps({"version": 99}))
    with pytest.raises(ValueError, match="version"):
        ckpt.load_grid(p)


def test_config_build_defaults():
    cfg = SimulationConfig(height=16, width=32, seed="glider")
    coordinator, scheduler = cfg.build()
    assert coordinator.shape == (16, 32)
    coordinator.tick()
    assert coordinator.population() == 5


def test_config_random_fill_overrides_default_seed():
    # regression: the default seed='glider' must not conflict with random_fill
    cfg = SimulationConfig(height=16, width=32, random_fill=0.3)
    c, _ = cfg.build()
    assert 0 < c.population() < 16 * 32


def test_config_mesh_parsing():
    cfg = SimulationConfig(mesh="2x4", height=16, width=256)
    c, _ = cfg.build()
    assert c.engine.mesh is not None
    with pytest.raises(ValueError):
        SimulationConfig(mesh="banana").build_mesh()


def test_config_sparse_opts_plumbing():
    # regression: --sparse-tile/--sparse-capacity must reach the sparse
    # engine (grids indivisible by the default 32x128 tile were unusable
    # from the CLI before sparse_opts was plumbed through the coordinator)
    cfg, _ = from_args(
        ["--grid", "48x256", "--topology", "dead", "--backend", "sparse",
         "--sparse-tile", "16x64", "--sparse-capacity", "64", "--seed", "glider"]
    )
    assert cfg.sparse_tile == (16, 64) and cfg.sparse_capacity == 64
    c, _ = cfg.build()
    sp = c.engine._sparse
    assert (sp.tile_rows, sp.tile_words, sp.capacity) == (16, 2, 64)
    c.tick(8)
    assert c.population() == 5
    with pytest.raises(ValueError):
        SimulationConfig(backend="sparse", sparse_tile=(16, 33)).build_sparse_opts()


def test_from_args_roundtrip():
    cfg, args = from_args(
        ["--grid", "128x128", "--rule", "highlife", "--seed", "random",
         "--random-p", "0.3", "--steps", "17", "--mesh", "auto",
         "--topology", "dead", "--population"]
    )
    assert (cfg.height, cfg.width) == (128, 128)
    assert cfg.random_fill == 0.3 and cfg.seed is None
    assert cfg.steps == 17 and cfg.track_population
    assert cfg.topology == "dead"


def test_cli_end_to_end(tmp_path, capsys):
    ck = tmp_path / "end.npz"
    rc = cli_main(
        ["--grid", "32x64", "--seed", "glider", "--steps", "8",
         "--render", "final", "--population", "--checkpoint", str(ck)]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "gen 8" in out and "pop 5" in out
    grid, meta = ckpt.load_grid(ck)
    assert meta["generation"] == 8
    assert grid.sum() == 5


def test_cli_resume(tmp_path):
    ck = tmp_path / "resume.npz"
    cli_main(["--grid", "32x64", "--seed", "glider", "--steps", "4",
              "--checkpoint", str(ck)])
    rc = cli_main(["--resume", str(ck), "--steps", "4", "--checkpoint", str(ck)])
    assert rc == 0
    grid, meta = ckpt.load_grid(ck)
    assert meta["generation"] == 8
    # 8 generations total = glider moved (2, 2)
    want = np.roll(seeds.seeded((32, 64), "glider", 14, 30), (2, 2), (0, 1))
    np.testing.assert_array_equal(grid, want)


def test_cli_elementary_spacetime(tmp_path, capsys):
    """VERDICT round-2 item #7: --rule W<N> drives the 1D family through
    the CLI — ASCII spacetime diagram + PPM artifact + population."""
    ppm = tmp_path / "w90.ppm"
    rc = cli_main(["--rule", "W90", "--grid", "1x64", "--steps", "16",
                   "--render", "final", "--population", "--ppm", str(ppm)])
    assert rc == 0
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if set(ln) <= {".", "#"} and ln]
    assert len(lines) == 17                       # steps+1 time rows
    assert lines[0].count("#") == 1               # single center seed
    # rule 90 = XOR of neighbors: row t has popcount 2^(popcount of t bits)
    assert lines[1].count("#") == 2 and lines[2].count("#") == 2
    assert "gen 16  pop" in out
    data = ppm.read_bytes()
    assert data.startswith(b"P6\n64 17\n255\n")   # W x (steps+1) image

    # oracle cross-check: the printed diagram IS evolve_spacetime's output
    import jax.numpy as jnp

    from gameoflifewithactors_tpu.models.elementary import parse_elementary
    from gameoflifewithactors_tpu.ops import bitpack
    from gameoflifewithactors_tpu.ops.elementary import evolve_spacetime

    row = np.zeros(64, np.uint8)
    row[32] = 1
    st = np.asarray(bitpack.unpack(evolve_spacetime(
        bitpack.pack(jnp.asarray(row[None])), 16,
        rule=parse_elementary("W90"))[:, 0, :]))
    printed = np.array([[c == "#" for c in ln] for ln in lines], dtype=np.uint8)
    np.testing.assert_array_equal(printed, st)


def test_cli_elementary_seeds_and_errors(tmp_path, capsys):
    # random / empty seeds route; 2D pattern names are rejected clearly
    rc = cli_main(["--rule", "W30", "--grid", "1x32", "--steps", "4",
                   "--seed", "random", "--population"])
    assert rc == 0 and "pop" in capsys.readouterr().out
    rc = cli_main(["--rule", "W30", "--grid", "1x32", "--steps", "2",
                   "--seed", "empty", "--population"])
    assert rc == 0 and "pop 0" in capsys.readouterr().out
    with pytest.raises(SystemExit, match="2D seed"):
        cli_main(["--rule", "W30", "--grid", "1x32", "--seed", "gosper_gun"])
    with pytest.raises(SystemExit, match="multiple of 32"):
        cli_main(["--rule", "W30", "--grid", "1x33"])


def test_cli_elementary_precedence_and_unsupported_flags(tmp_path, capsys):
    # --resume wins over a leftover --rule W<N>: the checkpointed 2D run
    # resumes instead of a silent fresh 1D run (review finding)
    ck = tmp_path / "r.npz"
    cli_main(["--grid", "32x64", "--seed", "glider", "--steps", "4",
              "--checkpoint", str(ck)])
    capsys.readouterr()
    rc = cli_main(["--resume", str(ck), "--rule", "W90", "--steps", "4",
                   "--render", "off", "--population"])
    assert rc == 0
    assert "pop 5" in capsys.readouterr().out     # the glider, not a 1D row
    # flags the 1D route cannot honor fail loudly instead of exiting 0
    # without the requested side effect
    with pytest.raises(SystemExit, match="not supported for 1D"):
        cli_main(["--rule", "W30", "--grid", "1x32", "--steps", "2",
                  "--checkpoint", str(tmp_path / "x.npz")])
    with pytest.raises(SystemExit, match="not supported for 1D"):
        cli_main(["--rule", "W30", "--grid", "1x32", "--metrics", "jsonl"])


def test_tiled_sparse_rejects_non_dividing_tile():
    import jax

    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.make_mesh((2, 2), jax.devices()[:4])
    with pytest.raises(ValueError, match="divisible into sparse tiles"):
        Engine(np.zeros((128, 256), np.uint8), "conway", mesh=m,
               backend="sparse", sparse_opts={"tile_rows": 10})


def test_cli_ppm_export_2d(tmp_path):
    ppm = tmp_path / "frame.ppm"
    rc = cli_main(["--grid", "32x64", "--seed", "glider", "--steps", "4",
                   "--ppm", str(ppm)])
    assert rc == 0
    assert ppm.read_bytes().startswith(b"P6\n64 32\n255\n")


def test_cli_rle_seed(tmp_path):
    rle = tmp_path / "p.rle"
    rle.write_text("x = 3, y = 3\nbob$2bo$3o!")
    rc = cli_main(["--grid", "32x64", "--seed", f"@{rle}", "--steps", "4"])
    assert rc == 0


def test_v3_packed_layout_roundtrips_all_backends(tmp_path):
    """v3 device-layout checkpoints reload bit-exactly across backends."""
    from gameoflifewithactors_tpu import Engine
    from gameoflifewithactors_tpu.models import seeds
    from gameoflifewithactors_tpu.utils import checkpoint as ckpt

    g = np.asarray(seeds.seeded((64, 96), "gosper_gun", 10, 10))
    src = Engine(g, "conway", topology=Topology.DEAD)
    src.step(37)
    path = ckpt.save(src, tmp_path / "gun.npz")
    for backend in ("packed", "dense", "sparse"):
        back = ckpt.load_engine(path, backend=backend)
        np.testing.assert_array_equal(back.snapshot(), src.snapshot())
        back.step(13)
    # and the words stored really are the packed device words (1 bit/cell)
    with np.load(path, allow_pickle=False) as z:
        assert z["words"].dtype == np.uint32
        assert z["words"].shape == (64, 3)


def test_unpack_np_roundtrip():
    from gameoflifewithactors_tpu.ops import bitpack

    rng = np.random.default_rng(8)
    g = rng.integers(0, 2, size=(33, 128), dtype=np.uint8)
    np.testing.assert_array_equal(bitpack.unpack_np(bitpack.pack_np(g)), g)


def test_cli_mesh_bands_end_to_end(capsys):
    """--mesh bands builds an (n, 1) row-band mesh and runs end-to-end."""
    from gameoflifewithactors_tpu.cli import main as cli_main
    from gameoflifewithactors_tpu.config import SimulationConfig

    m = SimulationConfig(height=64, width=64, mesh="bands").build_mesh()
    assert tuple(m.devices.shape) == (8, 1)
    rc = cli_main(["--grid", "64x64", "--seed", "glider", "--steps", "4",
                   "--mesh", "bands", "--render", "final", "--population"])
    assert rc == 0
    assert "gen 4" in capsys.readouterr().out


def test_multistate_ltl_checkpoint_across_layouts(tmp_path):
    """A C >= 3 LtL universe saved from the sharded banded plane engine
    reloads bit-exactly into every other serving layout (dense
    single-device, sparse planes, packed planes) and keeps evolving
    identically — the checkpoint story composed with both round-4
    features."""
    import jax

    from gameoflifewithactors_tpu import Engine
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib
    from gameoflifewithactors_tpu.utils import checkpoint as ckpt

    rng = np.random.default_rng(11)
    g = rng.integers(0, 4, size=(64, 128), dtype=np.uint8)
    spec = "R2,C4,M1,S3..8,B5..9"
    m = mesh_lib.make_mesh((2, 4), jax.devices())
    src = Engine(g, spec, mesh=m, backend="packed")   # sharded planes
    src.step(9)
    path = ckpt.save(src, tmp_path / "mltl.npz")
    want = src.snapshot()
    src.step(5)
    for backend in ("dense", "packed", "sparse"):
        back = ckpt.load_engine(path, backend=backend)
        np.testing.assert_array_equal(back.snapshot(), want)
        back.step(5)
        np.testing.assert_array_equal(back.snapshot(), src.snapshot(),
                                      err_msg=backend)


def test_cli_ppm_sequence_and_rle_round_trip(tmp_path, capsys):
    """--ppm-every writes an ffmpeg-ready full-resolution frame sequence
    (initial state included); --save-rle exports the final state as
    standard RLE that --seed @file.rle reloads bit-exactly (the Golly
    round trip)."""
    import glob

    import numpy as np

    from gameoflifewithactors_tpu.models import seeds as seeds_lib

    stem = tmp_path / "movie.ppm"
    rle = tmp_path / "final.rle"
    rc = cli_main(
        ["--grid", "16x32", "--seed", "glider", "--steps", "8",
         "--ppm", str(stem), "--ppm-every", "4", "--save-rle", str(rle)])
    assert rc == 0
    frames = sorted(glob.glob(str(tmp_path / "movie_*.ppm")))
    # gens 0 (seed), 4, 8 — and no single final movie.ppm write
    assert [f.rsplit("_", 1)[1] for f in frames] == [
        "000000.ppm", "000004.ppm", "000008.ppm"]
    assert not stem.exists()

    # the exported RLE reloads to the exact final state: glider at gen 8
    # on 16x32 has translated (2, 2) from its seeded origin
    reloaded = seeds_lib.from_rle(rle.read_text())
    ck = tmp_path / "after.npz"
    rc = cli_main(["--grid", "16x32", "--seed", f"@{rle}", "--seed-at", "0x0",
                   "--steps", "0", "--checkpoint", str(ck)])
    assert rc == 0
    grid, _ = ckpt.load_grid(ck)
    ys, xs = np.nonzero(grid)
    assert grid.sum() == 5 == reloaded.sum()


def test_cli_ppm_every_needs_stem_and_1d_rejects_rle(tmp_path):
    import pytest

    with pytest.raises(SystemExit, match="--ppm PATH"):
        cli_main(["--grid", "16x32", "--steps", "2", "--ppm-every", "2"])
    with pytest.raises(SystemExit, match="--save-rle"):
        cli_main(["--rule", "W30", "--grid", "1x32", "--steps", "2",
                  "--save-rle", str(tmp_path / "y.rle")])


def test_cli_save_rle_multistate_round_trip(tmp_path):
    """A Generations universe exports as Golly extended RLE and reloads
    bit-exactly through --seed @file.rle (dying states included)."""
    import numpy as np

    from gameoflifewithactors_tpu.models import seeds as seeds_lib

    rle = tmp_path / "brain.rle"
    ck1 = tmp_path / "a.npz"
    cli_main(["--grid", "16x32", "--seed", "random", "--rule", "brain",
              "--rng-seed", "3", "--steps", "3",
              "--save-rle", str(rle), "--checkpoint", str(ck1)])
    grid1, _ = ckpt.load_grid(ck1)
    assert grid1.max() > 1, "want dying states in the exported universe"
    assert "rule = brain" in rle.read_text()
    np.testing.assert_array_equal(seeds_lib.from_rle(rle.read_text()), grid1)

    ck2 = tmp_path / "b.npz"
    cli_main(["--grid", "16x32", "--seed", f"@{rle}", "--seed-at", "0x0",
              "--rule", "brain", "--steps", "0", "--checkpoint", str(ck2)])
    grid2, _ = ckpt.load_grid(ck2)
    np.testing.assert_array_equal(grid2, grid1)


def test_cli_list_registries(capsys):
    rc = cli_main(["--list"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "gosper_gun" in out and "B3/S23" in out
    assert "brain" in out and "bosco" in out and "W0..W255" in out
