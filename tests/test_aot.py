"""Warm-start subsystem tests (aot/): cache wiring, EngineSpec identity,
AOT serialize/deserialize round-trips, compile-event attribution, and the
StepMetrics compile_seconds == 0 regression for cache-hit runs.

Everything runs against throwaway cache dirs (the ``cold_compile_cache``
fixture / monkeypatched ``GOLTPU_CACHE_DIR``) — the session-level cache
tests/conftest.py sets up must never make these tests order-dependent.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

from gameoflifewithactors_tpu.aot import (
    EngineSpec,
    cache as aot_cache,
    registry as aot_registry,
    warmup as aot_warmup,
)
from gameoflifewithactors_tpu.obs import compile as obs_compile


def _soup(shape=(64, 64), states=2, seed=0):
    return np.random.default_rng(seed).integers(
        0, states, size=shape, dtype=np.uint8)


# -- layer 1: the persistent compilation cache --------------------------------


def test_resolve_cache_root_precedence(monkeypatch, tmp_path):
    monkeypatch.delenv(aot_cache.ENV_CACHE_DIR, raising=False)
    assert aot_cache.resolve_cache_root() == aot_cache.default_cache_root()
    monkeypatch.setenv(aot_cache.ENV_CACHE_DIR, str(tmp_path / "env"))
    assert aot_cache.resolve_cache_root() == str(tmp_path / "env")
    # an explicit path beats the env; empty-string explicit disables
    assert aot_cache.resolve_cache_root(str(tmp_path / "x")) == str(tmp_path / "x")
    assert aot_cache.resolve_cache_root("") is None
    # the documented off-switch spellings
    for off in ("", "0", "off", "none", "OFF"):
        monkeypatch.setenv(aot_cache.ENV_CACHE_DIR, off)
        assert aot_cache.resolve_cache_root() is None


def test_ensure_persistent_cache_points_jax_at_the_dir(cold_compile_cache):
    import jax

    assert jax.config.jax_compilation_cache_dir == os.path.join(
        cold_compile_cache, "xla")
    assert aot_cache.current_cache_dir() == os.path.join(
        cold_compile_cache, "xla")
    # zeroed thresholds: every runner is cacheable, not just the slow tail
    assert jax.config.jax_persistent_cache_min_entry_size_bytes == 0
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0


def test_compile_lands_in_the_cache_dir(cold_compile_cache):
    from gameoflifewithactors_tpu.engine import Engine

    eng = Engine(_soup(), "B3/S23", backend="packed")
    eng.step(3)
    eng.block_until_ready()
    entries = os.listdir(os.path.join(cold_compile_cache, "xla"))
    assert any(n.endswith("-cache") for n in entries), \
        "the engine's compiles must round-trip through the disk cache"


def test_cache_hit_attribution_after_clear(cold_compile_cache):
    """The attribution at the heart of the warm path: a jit-cache miss
    whose executable came from the persistent disk cache is a
    ``cache_hit`` event, and contributes ZERO compile seconds."""
    import jax
    import jax.numpy as jnp

    from gameoflifewithactors_tpu.ops._jit import optionally_donated

    @optionally_donated("p", static=())
    def _aot_probe(p):
        return (p << 1) ^ p

    log = obs_compile.CompileEventLog()
    x = jnp.full((8, 8), 3, jnp.uint32)
    obs_compile.tracked_call(_aot_probe.jitted, "_aot_probe", (x,), {},
                             log=log)
    ev = log.events()[0]
    assert ev.kind == "cache_miss" and ev.cache_miss
    # a fresh process re-traces but reads the executable from disk;
    # jax.clear_caches() reproduces that state in-process
    jax.clear_caches()
    obs_compile.tracked_call(_aot_probe.jitted, "_aot_probe", (x,), {},
                             log=log)
    warm = log.events()[-1]
    assert warm.kind == "cache_hit" and not warm.cache_miss
    # only the real compile counts toward compile seconds
    assert log.total_compile_seconds() == pytest.approx(ev.wall_seconds)


# -- EngineSpec identity ------------------------------------------------------


def test_spec_canonical_and_key():
    a = EngineSpec(height=64, width=64, rule="conway", backend="packed")
    b = EngineSpec(height=64, width=64, rule="B3/S23", backend="packed")
    env = {"jax": "x", "jaxlib": "y", "platform": "cpu",
           "device_kind": "cpu", "device_count": 1}
    # named and notation spellings of one rule share artifacts
    assert a.canonical() == b.canonical()
    assert a.cache_key(env) == b.cache_key(env)
    # any spec field or environment field re-keys
    c = EngineSpec(height=64, width=96, rule="B3/S23", backend="packed")
    assert c.cache_key(env) != a.cache_key(env)
    assert a.cache_key({**env, "jaxlib": "z"}) != a.cache_key(env)


def test_spec_from_dict_shapes_and_errors():
    s = EngineSpec.from_dict({"rule": "brain", "shape": [128, 256]})
    assert (s.height, s.width) == (128, 256) and s.backend == "auto"
    with pytest.raises(ValueError, match="unknown EngineSpec fields"):
        EngineSpec.from_dict({"shape": [8, 8], "gird": "typo"})


def test_spec_engine_round_trip():
    spec = EngineSpec(height=64, width=64, rule="B3/S23", backend="auto")
    resolved = spec.resolve()
    assert resolved.backend in ("packed", "pallas")
    eng = resolved.build_engine()
    assert EngineSpec.from_engine(eng) == resolved


# -- layer 2: AOT serialize -> fresh-process-style deserialize -> step --------


@pytest.mark.parametrize("rule,states", [
    ("B3/S23", 2),          # binary packed words
    ("brain", 3),           # Generations bit-plane stack
])
def test_aot_round_trip_bit_identity(rule, states, cold_compile_cache,
                                     monkeypatch):
    """serialize -> deserialize (jit caches dropped, as in a fresh
    process) -> step must be bit-identical with the JIT path."""
    import jax

    spec = EngineSpec(height=64, width=64, rule=rule, backend="packed")
    grid = _soup(states=states)
    jit_eng = spec.build_engine(grid)
    assert not jit_eng.aot_loaded  # nothing registered yet
    aot_registry.serialize_engine(jit_eng)
    jit_eng.step(7)
    ref = jit_eng.snapshot()

    jax.clear_caches()  # fresh-process stand-in: no live executables
    aot_eng = spec.build_engine(grid)
    assert aot_eng.aot_loaded, "registered artifact must be picked up"
    assert getattr(aot_eng._run, "aot_key", None)
    aot_eng.step(7)
    np.testing.assert_array_equal(aot_eng.snapshot(), ref)

    # the off-switch keeps the JIT path
    monkeypatch.setenv(aot_registry.ENV_AOT, "0")
    off = spec.build_engine(grid)
    assert not off.aot_loaded


def test_aot_load_records_event(cold_compile_cache):
    spec = EngineSpec(height=64, width=64, rule="B3/S23", backend="packed")
    eng = spec.build_engine()
    aot_registry.serialize_engine(eng)
    obs_compile.COMPILE_LOG.clear()
    loaded = spec.build_engine()
    assert loaded.aot_loaded
    kinds = [e.kind for e in obs_compile.COMPILE_LOG.events()]
    assert "aot_loaded" in kinds
    # an AOT load is not compile time
    assert obs_compile.COMPILE_LOG.total_compile_seconds() == 0.0


def test_aot_corrupt_artifact_falls_back_with_warning(cold_compile_cache):
    spec = EngineSpec(height=64, width=64, rule="B3/S23", backend="packed")
    eng = spec.build_engine()
    blob_path = aot_registry.serialize_engine(eng)
    with open(blob_path, "wb") as f:
        f.write(b"not a jax.export blob")
    with pytest.warns(RuntimeWarning, match="failed to load"):
        assert aot_registry.load_runner(spec) is None
    fresh = spec.build_engine()  # engine constructor takes the same path
    assert not fresh.aot_loaded


def test_aot_environment_mismatch_warns(cold_compile_cache):
    spec = EngineSpec(height=64, width=64, rule="B3/S23", backend="packed")
    eng = spec.build_engine()
    aot_registry.serialize_engine(eng)
    reg = aot_cache.aot_registry_dir()
    (meta_name,) = [n for n in os.listdir(reg) if n.endswith(".json")]
    meta = json.load(open(os.path.join(reg, meta_name)))
    meta["env"]["jaxlib"] = "0.0.0-elsewhere"
    other_key = "f" * 24
    json.dump(meta, open(os.path.join(reg, other_key + ".json"), "w"))
    # drop the matching artifact so only the foreign-env one remains
    for n in (meta_name, meta_name.replace(".json", ".jaxexport")):
        os.remove(os.path.join(reg, n))
    with pytest.warns(RuntimeWarning, match="different environment"):
        assert aot_registry.load_runner(spec) is None


def test_aot_unsupported_configs_raise_and_skip():
    from gameoflifewithactors_tpu.engine import Engine
    from gameoflifewithactors_tpu.parallel import mesh as mesh_lib

    sharded = Engine(_soup((64, 64)), "B3/S23",
                     mesh=mesh_lib.make_mesh((8, 1)), backend="packed")
    with pytest.raises(aot_registry.AotUnsupported, match="sharded"):
        aot_registry._exportable_runner(sharded)
    assert aot_registry.maybe_load_for_engine(sharded) is None
    sparse = Engine(_soup((64, 64)), "B3/S23", backend="sparse")
    with pytest.raises(aot_registry.AotUnsupported, match="sparse"):
        aot_registry._exportable_runner(sparse)


# -- the StepMetrics regression: compile_seconds == 0 on a cache-hit run ------


def test_step_metrics_zero_compile_on_cache_hit_run(cold_compile_cache):
    """ISSUE-2 regression: when every executable comes from the
    persistent cache, the tick's StepMetrics must report no compile
    seconds — the warm path's whole claim, in the metric users watch."""
    import jax

    from gameoflifewithactors_tpu.coordinator import GridCoordinator
    from gameoflifewithactors_tpu.utils.metrics import BufferSink, MetricsLogger

    cold_buf = BufferSink()
    coord = GridCoordinator((48, 64), "B36/S125", random_fill=0.3,
                            backend="packed",
                            metrics=MetricsLogger(cold_buf))
    coord.tick(2)
    assert cold_buf.records[0].compile_seconds, \
        "cold run must pay (and report) the compile"

    jax.clear_caches()  # fresh-process stand-in
    warm_buf = BufferSink()
    coord2 = GridCoordinator((48, 64), "B36/S125", random_fill=0.3,
                             backend="packed",
                             metrics=MetricsLogger(warm_buf))
    t0 = time.perf_counter()
    coord2.tick(2)
    t1 = time.perf_counter()
    rec = warm_buf.records[0]
    assert rec.compile_seconds is None  # == 0 in the serialized record
    # ... and not because nothing happened: the runner DID re-enter the
    # jit cache inside this tick, served from disk
    hits = [e for e in obs_compile.COMPILE_LOG.events()
            if e.kind == "cache_hit" and t0 <= e.t1 <= t1]
    assert hits, "the warm tick must record its cache_hit attribution"


# -- layer 3: the warmup pipeline ---------------------------------------------


def test_warmup_specs_populates_both_layers(cold_compile_cache):
    import jax

    jax.clear_caches()  # earlier tests may hold this runner in-memory
    specs = [EngineSpec(height=64, width=64, rule="B3/S23",
                        backend="packed")]
    rows = aot_warmup.warmup_specs(specs, verbose=None)
    assert rows[0]["aot"] == "serialized"
    assert rows[0]["resolved_backend"] == "packed"
    xla = os.listdir(os.path.join(cold_compile_cache, "xla"))
    assert any(n.endswith("-cache") for n in xla)
    reg = os.listdir(os.path.join(cold_compile_cache, "aot"))
    assert any(n.endswith(".jaxexport") for n in reg)
    assert any(n.endswith(".json") for n in reg)


def test_warmup_manifest_loader(tmp_path):
    path = tmp_path / "manifest.json"
    path.write_text(json.dumps([
        {"rule": "B3/S23", "shape": [64, 64], "backend": "packed"},
        {"rule": "brain", "height": 64, "width": 64},
    ]))
    specs = aot_warmup.load_manifest(str(path))
    assert [s.rule for s in specs] == ["B3/S23", "brain"]
    path.write_text(json.dumps({"not": "a list"}))
    with pytest.raises(ValueError, match="JSON list"):
        aot_warmup.load_manifest(str(path))


def test_warmup_cli_from_config(cold_compile_cache, capsys):
    from gameoflifewithactors_tpu import cli

    rc = cli.main(["warmup", "--from-config", "--json", "--no-aot",
                   "--grid", "64x64", "--rule", "B3/S23",
                   "--backend", "packed"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["warmup"] and len(out["specs"]) == 1
    assert out["specs"][0]["spec"]["rule"] == "B3/S23"
    with pytest.raises(SystemExit):  # exactly one mode is required
        cli.main(["warmup"])
