"""The resilience layer (resilience/): supervised restart-with-rollback,
deterministic fault plans, and the crash-safe checkpoint discipline they
stand on.

Covers the ISSUE-11 acceptance points: bit-exact restore after a
validator trip and after injected corruption (recovered run == unfaulted
oracle, generation for generation); the capped-exponential backoff
schedule and the max-restarts circuit breaker; FaultPlan determinism and
JSON round-trip; stall detection wired through the StallWatchdog with a
flight dump; retrace injection attributed by the supervisor's sentinel;
and the kill-during-save subprocess test proving ``checkpoint.save``
never leaves a torn file where a good checkpoint used to be.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from gameoflifewithactors_tpu.coordinator import GridCoordinator
from gameoflifewithactors_tpu.obs import flight as obs_flight
from gameoflifewithactors_tpu.obs import watchdog as obs_watchdog
from gameoflifewithactors_tpu.resilience import (
    ALL_KINDS,
    CircuitOpenError,
    FaultEvent,
    FaultPlan,
    RestartPolicy,
    Supervisor,
    apply_fault,
)
from gameoflifewithactors_tpu.utils import checkpoint as ckpt_lib
from gameoflifewithactors_tpu.utils import fault as fault_lib


def _coordinator(backend="dense", shape=(64, 64), seed=7):
    return GridCoordinator(shape, "B3/S23", random_fill=0.35,
                           rng_seed=seed, backend=backend)


def _oracle_grid(generations, backend="dense", shape=(64, 64), seed=7):
    c = _coordinator(backend=backend, shape=shape, seed=seed)
    c.tick(generations)
    return c.snapshot()


# -- the restart policy in isolation ------------------------------------------


def test_backoff_is_capped_exponential():
    p = RestartPolicy(backoff_initial_seconds=0.1, backoff_max_seconds=1.0,
                      backoff_factor=2.0)
    assert [p.backoff(n) for n in (1, 2, 3, 4, 5, 6)] == \
        [0.1, 0.2, 0.4, 0.8, 1.0, 1.0]


def test_supervisor_rejects_bad_cadence(tmp_path):
    with pytest.raises(ValueError, match="checkpoint_every"):
        Supervisor(_coordinator(), checkpoint_path=str(tmp_path / "c.npz"),
                   checkpoint_every=0)


# -- rollback correctness ------------------------------------------------------


@pytest.mark.parametrize("backend", ["dense", "packed"])
def test_injected_corruption_recovers_bit_exact(tmp_path, backend):
    """A corrupted-then-restored run ends bit-identical to a run that
    never faulted — restores come from validated checkpoints and the
    lost generations are replayed deterministically."""
    sup = Supervisor(_coordinator(backend=backend),
                     checkpoint_path=str(tmp_path / "c.npz"),
                     checkpoint_every=20, sleep_fn=lambda s: None)
    fired = []

    def before_chunk(gen):
        if 20 <= gen < 40 and not fired:
            fired.append(gen)
            sup.inject("corrupt_region",
                       lambda e: fault_lib.corrupt_region(
                           e, 4, 4, 16, 16, seed=99))

    sup.before_chunk = before_chunk
    stats = sup.run(100)
    assert fired, "the fault never fired — the test is vacuous"
    assert stats["restarts_by_cause"] == {"fault:corrupt_region": 1}
    assert stats["generation"] == 100
    np.testing.assert_array_equal(sup.coordinator.snapshot(),
                                  _oracle_grid(100, backend=backend))


def test_validator_trip_restores_and_counts(tmp_path):
    """Dropping the whole grid trips the min-population validator; the
    supervisor rolls back and the final state still matches the oracle."""
    coordinator = _coordinator()
    h, w = coordinator.engine.shape
    sup = Supervisor(coordinator, checkpoint_path=str(tmp_path / "c.npz"),
                     checkpoint_every=25,
                     validators=[fault_lib.population_bounds_validator(
                         min_pop=1)],
                     sleep_fn=lambda s: None)
    dropped = []

    def before_chunk(gen):
        if gen == 25 and not dropped:
            dropped.append(gen)
            # bypass inject(): an *undetected* fault, found by the
            # validator at the boundary, is the channel under test
            fault_lib.drop_region(coordinator.engine, 0, 0, h, w)

    sup.before_chunk = before_chunk
    stats = sup.run(75)
    assert dropped
    assert stats["validator_trips"] == 1
    assert stats["restarts_by_cause"] == {"validator": 1}
    np.testing.assert_array_equal(coordinator.snapshot(), _oracle_grid(75))


def test_restore_resumes_generation_for_generation(tmp_path):
    """After a restart, every subsequent chunk boundary lands on the
    same generations the oracle passes through."""
    sup = Supervisor(_coordinator(), checkpoint_path=str(tmp_path / "c.npz"),
                     checkpoint_every=10, sleep_fn=lambda s: None)
    seen = []
    faulted = []

    def before_chunk(gen):
        seen.append(gen)
        if gen == 20 and not faulted:
            faulted.append(gen)
            sup.inject("drop_region",
                       lambda e: fault_lib.drop_region(e, 0, 0, 8, 8))

    sup.before_chunk = before_chunk
    sup.run(50)
    # gen 20 appears twice: once faulted, once replayed clean
    assert seen == [0, 10, 20, 20, 30, 40]
    for boundary_gen in (10, 20, 30, 40, 50):
        np.testing.assert_array_equal(
            sup.coordinator.snapshot() if boundary_gen == 50 else
            _oracle_grid(boundary_gen), _oracle_grid(boundary_gen))


def test_on_restart_callback_and_notify(tmp_path):
    coordinator = _coordinator()
    calls = []
    frames = []
    coordinator.subscribe(lambda frame: frames.append(frame.generation))
    sup = Supervisor(coordinator, checkpoint_path=str(tmp_path / "c.npz"),
                     checkpoint_every=10, sleep_fn=lambda s: None,
                     on_restart=lambda *a: calls.append(a))
    sup.before_chunk = (lambda gen: sup.inject(
        "drop_region", lambda e: fault_lib.drop_region(e, 0, 0, 4, 4))
        if gen == 10 and not calls else None)
    sup.run(30)
    assert calls == [("fault:drop_region", 10, 1)]
    # subscribers saw the rollback notify (generation 10 re-announced)
    assert frames.count(10) >= 2


# -- backoff + circuit breaker -------------------------------------------------


def test_backoff_schedule_honored_then_circuit_opens(tmp_path):
    """A fault injected before *every* chunk fails forever: the recorded
    sleeps must follow the policy's capped exponential, and the breaker
    must open after max_restarts consecutive failures."""
    sleeps = []
    policy = RestartPolicy(max_restarts=4, backoff_initial_seconds=0.1,
                           backoff_max_seconds=0.4, backoff_factor=2.0)
    sup = Supervisor(_coordinator(), checkpoint_path=str(tmp_path / "c.npz"),
                     checkpoint_every=10, policy=policy,
                     sleep_fn=sleeps.append)
    sup.before_chunk = lambda gen: sup.inject(
        "corrupt_region",
        lambda e: fault_lib.corrupt_region(e, 0, 0, 8, 8, seed=1))
    with pytest.raises(CircuitOpenError, match="max_restarts=4"):
        sup.run(100)
    assert sleeps == [0.1, 0.2, 0.4, 0.4]  # 4 restarts, then give up
    stats = sup.stats()
    assert stats["circuit_open"] is True
    assert stats["restarts"] == 4


def test_clean_chunk_resets_failure_streak(tmp_path):
    """max_restarts counts *consecutive* failures: alternating
    fault/clean chunks never open the circuit."""
    sup = Supervisor(_coordinator(),
                     checkpoint_path=str(tmp_path / "c.npz"),
                     checkpoint_every=10,
                     policy=RestartPolicy(max_restarts=1),
                     sleep_fn=lambda s: None)
    flips = {"n": 0}

    def before_chunk(gen):
        flips["n"] += 1
        if flips["n"] % 2:
            sup.inject("drop_region",
                       lambda e: fault_lib.drop_region(e, 0, 0, 4, 4))

    sup.before_chunk = before_chunk
    stats = sup.run(40)  # 4 clean chunks needed; ~8 boundary visits
    assert stats["restarts"] == 4
    assert stats["circuit_open"] is False
    np.testing.assert_array_equal(sup.coordinator.snapshot(), _oracle_grid(40))


# -- stall + retrace channels --------------------------------------------------


def test_stall_detected_restored_and_flight_dumped(tmp_path):
    """An induced stall (subscriber sleeping past the deadline inside
    the watched tick) is flagged by the armed watchdog, dumps flight,
    and the supervisor restores — final grid still oracle-exact."""
    wd = obs_watchdog.arm(obs_watchdog.StallWatchdog(0.3))
    fr = obs_flight.FlightRecorder(str(tmp_path / "flight.jsonl"))
    fr.install(watchdog=wd)  # before arm(): see resilience/worker.py
    obs_flight.arm(fr)
    try:
        from gameoflifewithactors_tpu.resilience import induce_stall

        sup = Supervisor(_coordinator(),
                         checkpoint_path=str(tmp_path / "c.npz"),
                         checkpoint_every=15, sleep_fn=lambda s: None)
        stalled = []

        def before_chunk(gen):
            if gen == 15 and not stalled:
                stalled.append(gen)
                sup.inject("stall", lambda e: induce_stall(
                    sup.coordinator, 0.8))

        sup.before_chunk = before_chunk
        stats = sup.run(45)
        assert stats["stalls_detected"] >= 1
        assert stats["restarts_by_cause"] == {"fault:stall": 1}
        assert fr.dumps >= 1
        assert "stall" in (fr.last_dump_reason or "")
        np.testing.assert_array_equal(sup.coordinator.snapshot(),
                                      _oracle_grid(45))
    finally:
        obs_flight.disarm()
        obs_watchdog.disarm()


def test_induced_retrace_attributed_not_rolled_back(tmp_path):
    from gameoflifewithactors_tpu.resilience import induce_retrace

    sup = Supervisor(_coordinator(), checkpoint_path=str(tmp_path / "c.npz"),
                     checkpoint_every=10, sleep_fn=lambda s: None)
    poked = []

    def before_chunk(gen):
        if gen == 10 and not poked:
            poked.append(gen)
            sup.inject("retrace", lambda e: induce_retrace())

    sup.before_chunk = before_chunk
    stats = sup.run(30)
    assert poked
    assert stats["retraces_attributed"] == 1
    assert stats["restarts"] == 0  # no state harmed, no rollback
    np.testing.assert_array_equal(sup.coordinator.snapshot(), _oracle_grid(30))


# -- FaultPlan -----------------------------------------------------------------


def test_faultplan_same_seed_same_schedule():
    a = FaultPlan.generate(123, workers=4, horizon=200,
                           ensure_kinds=("stall", "retrace"),
                           kill_workers=(1,))
    b = FaultPlan.generate(123, workers=4, horizon=200,
                           ensure_kinds=("stall", "retrace"),
                           kill_workers=(1,))
    assert a == b
    c = FaultPlan.generate(124, workers=4, horizon=200,
                           ensure_kinds=("stall", "retrace"),
                           kill_workers=(1,))
    assert a != c


def test_faultplan_json_roundtrip_and_coverage():
    plan = FaultPlan.generate(5, workers=3, horizon=120,
                              faults_per_worker=4,
                              ensure_kinds=("corrupt_region", "stall",
                                            "retrace"),
                              kill_workers=(0, 2))
    again = FaultPlan.from_json(plan.to_json())
    assert again == plan
    kinds = set(plan.kinds())
    assert {"corrupt_region", "stall", "retrace", "kill"} <= kinds
    assert kinds <= set(ALL_KINDS)
    lo, hi = 120 // 4, (3 * 120) // 4
    for e in plan.events:
        assert lo <= e.at_gen <= hi
    assert [e.worker for e in plan.for_worker(2)] == \
        [2] * len(plan.for_worker(2))
    assert all(e.kind == "kill" for e in plan.for_worker(0, kinds=("kill",)))


def test_faultplan_rejects_degenerate_inputs():
    with pytest.raises(ValueError, match="workers"):
        FaultPlan.generate(0, workers=0, horizon=100)
    with pytest.raises(ValueError, match="horizon"):
        FaultPlan.generate(0, workers=1, horizon=4)


def test_apply_fault_degrades_shard_kinds_without_mesh(tmp_path):
    """On an unsharded engine the shard kinds degrade to region form —
    one plan stays valid across every worker flavor."""
    sup = Supervisor(_coordinator(), checkpoint_path=str(tmp_path / "c.npz"),
                     checkpoint_every=10, sleep_fn=lambda s: None)
    hits = []
    sup.before_chunk = (lambda gen: hits.append(apply_fault(
        sup, FaultEvent(worker=0, at_gen=10, kind="drop_shard",
                        params={"shard_f": 0.5})))
        if gen == 10 and not hits else None)
    stats = sup.run(30)
    assert hits == ["drop_region"]
    assert stats["restarts_by_cause"] == {"fault:drop_region": 1}
    np.testing.assert_array_equal(sup.coordinator.snapshot(), _oracle_grid(30))


# -- crash-safe checkpoint save ------------------------------------------------

_KILL_LOOP = """
import sys
from gameoflifewithactors_tpu.coordinator import GridCoordinator
from gameoflifewithactors_tpu.utils import checkpoint as ckpt_lib

c = GridCoordinator((64, 64), "B3/S23", random_fill=0.4, rng_seed=3,
                    backend="dense")
path = sys.argv[1]
ckpt_lib.save(c.engine, path)
print("FIRST_SAVE_DONE", flush=True)
while True:
    c.tick(1)
    ckpt_lib.save(c.engine, path)
"""


def test_kill_during_save_leaves_previous_checkpoint_intact(tmp_path):
    """SIGKILL a process that is saving in a tight loop; whatever made
    it to ``path`` must still be a loadable checkpoint (the atomic
    tmp+rename discipline), never a torn write."""
    path = tmp_path / "ck.npz"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", _KILL_LOOP, str(path)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, env=env, text=True)
    try:
        line = proc.stdout.readline()
        assert "FIRST_SAVE_DONE" in line
        # let it overwrite mid-flight a few times, then kill without grace
        time.sleep(1.0)
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    grid, meta = ckpt_lib.load_grid(path)
    assert grid.shape == (64, 64)
    assert meta["generation"] >= 0
    # no abandoned temp file masquerading as the checkpoint
    leftovers = [p for p in tmp_path.iterdir() if ".tmp" in p.name]
    for p in leftovers:  # tolerated on disk, but never the load target
        assert p.name != path.name


def test_save_failure_cleans_up_temp_file(tmp_path, monkeypatch):
    c = _coordinator(shape=(32, 32))
    path = tmp_path / "ck.npz"
    real_replace = os.replace

    def boom(src, dst):
        raise OSError("disk full")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError, match="disk full"):
        ckpt_lib.save(c.engine, path)
    monkeypatch.setattr(os, "replace", real_replace)
    assert list(tmp_path.iterdir()) == []  # tmp unlinked, nothing torn


# -- the worker, end to end ----------------------------------------------------


@pytest.mark.slow
def test_worker_subprocess_recovers_and_reports(tmp_path):
    """One soak worker process: injected corruption + retrace, exits 0,
    report shows the restart and the attribution."""
    plan = [
        FaultEvent(worker=0, at_gen=30, kind="corrupt_region",
                   params={"top_f": 0.1, "left_f": 0.1, "h_f": 0.25,
                           "w_f": 0.25, "seed": 11}).to_dict(),
        FaultEvent(worker=0, at_gen=50, kind="retrace").to_dict(),
    ]
    workdir = tmp_path / "w0"
    spec = {"name": "t-worker", "flavor": "packed", "shape": [64, 64],
            "generations": 80, "checkpoint_every": 20, "rng_seed": 5,
            "workdir": str(workdir), "watchdog_deadline": 5.0,
            "events": plan}
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(spec))
    env = dict(os.environ, JAX_PLATFORMS="cpu", GOLTPU_SANITIZE="1")
    proc = subprocess.run(
        [sys.executable, "-m", "gameoflifewithactors_tpu.resilience.worker",
         "--spec", str(spec_path)],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.startswith("METRICS_PORT ")
    report = json.loads((workdir / "report.json").read_text())
    assert report["ok"] is True
    member = report["members"][0]
    assert member["final_generation"] == 80
    assert member["supervisor"]["restarts_by_cause"] == \
        {"fault:corrupt_region": 1}
    assert member["supervisor"]["retraces_attributed"] == 1
    assert (workdir / "final.npy").exists()


# -- checkpoint rot as a routine event (ISSUE 14 satellites) -------------------

def test_load_grid_corrupt_npz_raises_clean_error(tmp_path):
    """Truncated or byte-flipped NPZ surfaces as CheckpointCorruptError
    (a ValueError), never a raw zipfile/zlib traceback; a missing file
    stays FileNotFoundError — absence is not damage."""
    c = _coordinator(shape=(32, 32))
    path = tmp_path / "ck.npz"
    ckpt_lib.save(c.engine, path)

    whole = path.read_bytes()
    (tmp_path / "truncated.npz").write_bytes(whole[: len(whole) // 2])
    with pytest.raises(ckpt_lib.CheckpointCorruptError):
        ckpt_lib.load_grid(tmp_path / "truncated.npz")

    (tmp_path / "junk.npz").write_bytes(b"this was never a checkpoint")
    with pytest.raises(ckpt_lib.CheckpointCorruptError):
        ckpt_lib.load_grid(tmp_path / "junk.npz")

    flipped = tmp_path / "flipped.npz"
    flipped.write_bytes(whole)
    fault_lib.corrupt_checkpoint_file(flipped, seed=0)
    with pytest.raises(ValueError):  # the subclass contract: old call
        ckpt_lib.load_grid(flipped)  # sites catching ValueError still work

    with pytest.raises(FileNotFoundError):
        ckpt_lib.load_grid(tmp_path / "never-existed.npz")


def test_supervisor_falls_back_to_previous_checkpoint(tmp_path):
    """A rotted current checkpoint is a routine fall-back-to-.prev at
    restart, not a crash — and the replay from the older restore point
    still converges bit-exactly."""
    path = tmp_path / "ck.npz"
    sup = Supervisor(_coordinator(), checkpoint_path=str(path),
                     checkpoint_every=10, sleep_fn=lambda s: None)
    corrupted = []

    def before_chunk(gen):
        if gen == 30 and not corrupted:
            # rot the live checkpoint, then trip a detected fault so the
            # supervisor must restore right through the rot
            fault_lib.corrupt_checkpoint_file(path, seed=4)
            corrupted.append(gen)
            sup.inject("corrupt_region",
                       lambda e: fault_lib.corrupt_region(e, 0, 0, 8, 8,
                                                          seed=9))

    sup.before_chunk = before_chunk
    stats = sup.run(50)
    assert stats["checkpoint_fallbacks"] == 1
    assert stats["restarts"] >= 1
    assert (tmp_path / "ck.npz.prev").exists()
    np.testing.assert_array_equal(sup.coordinator.snapshot(),
                                  _oracle_grid(50))


def test_faultplan_driver_kinds_schedule_and_refuse_in_process():
    """The distributed kinds ride the same seeded/JSON plan machinery,
    are never drawn for in-process workers, and in-process application
    refuses them by construction."""
    from gameoflifewithactors_tpu.resilience import DRIVER_KINDS

    plan = FaultPlan.generate(
        7, workers=2, horizon=120, faults_per_worker=0,
        kinds=DRIVER_KINDS,
        ensure_kinds=("process_kill", "process_preempt",
                      "checkpoint_corrupt"))
    kinds = plan.kinds()
    assert kinds == ["checkpoint_corrupt", "process_kill",
                     "process_preempt"]
    assert FaultPlan.from_json(plan.to_json()) == plan
    assert plan == FaultPlan.generate(
        7, workers=2, horizon=120, faults_per_worker=0,
        kinds=DRIVER_KINDS,
        ensure_kinds=("process_kill", "process_preempt",
                      "checkpoint_corrupt"))
    by_kind = {e.kind: e for e in plan.events}
    assert by_kind["process_preempt"].params["grace_seconds"] > 0
    assert "seed" in by_kind["checkpoint_corrupt"].params

    # random draws must never produce a driver kind
    spray = FaultPlan.generate(11, workers=3, horizon=120,
                               faults_per_worker=5)
    assert not {e.kind for e in spray.events} & set(DRIVER_KINDS)
    # asking for random draws from a driver-only pool is a planning bug
    with pytest.raises(ValueError, match="in-process"):
        FaultPlan.generate(0, workers=1, horizon=100,
                           faults_per_worker=1, kinds=("process_kill",))

    sup = Supervisor(_coordinator(), checkpoint_path=str("unused.npz"),
                     checkpoint_every=10, sleep_fn=lambda s: None)
    with pytest.raises(ValueError, match="fleet driver"):
        apply_fault(sup, by_kind["process_kill"])
